"""JAX extension-field tower Fp2 -> Fp6 -> Fp12 for BN254.

Mirrors the scalar tower in ops/bn254_ref.py (the correctness oracle) on limb
vectors. TPU-first structure: every tower multiplication flattens its
independent base-field multiplications into the *batch* dimension and issues a
single `Field.mul` call —

    Fp12 mul = 3 Fp6 muls = 18 Fp2 muls = 54 Fp muls  ->  ONE mont_mul at 54xB

so the Pallas kernel's lanes stay full even for small pairing batches
(ops/fp.py "batch stacking beats vmap"). Elements are pytrees of (nlimbs, B)
uint32 arrays: Fp2 = (c0, c1), Fp6 = (Fp2, Fp2, Fp2), Fp12 = (Fp6, Fp6).

All values Montgomery-form, canonical (< p) — EXCEPT under the resident
field adapter (`Tower.as_resident()`, ops/rns.py `ResidentRns`), where every
coordinate is a (k_all, B) int32 joint-residue array bounded by 2^lb * p for
a statically-tracked exponent lb. The tower formulas are representation-
agnostic; the only resident-specific obligation is the `blog` literal passed
at each subtraction/negation site — the static bound exponent of the
subtrahend at that site, derived once by the bound walk in HACKING.md
"Residue-resident pairing" and simply ignored by the positional backends.
"""

from __future__ import annotations

import jax.numpy as jnp

from handel_tpu.ops import bn254_ref as bn
from handel_tpu.ops.fp import Field


def _split3(x):
    b = x.shape[1] // 3
    return x[:, :b], x[:, b : 2 * b], x[:, 2 * b :]


class Tower:
    """Fp2/Fp6/Fp12 arithmetic over a base Field (tower shape shared by BN254
    and BLS12-381: i^2 = -1, v^3 = xi, w^2 = v).

    `params` is the scalar-oracle module defining the curve family's field
    constants — P, XI, _GAMMA, and (for BN) U. Defaults to BN254
    (ops/bn254_ref.py); pass ops/bls12_381_ref for the 381-bit tower with
    xi = 1 + i."""

    def __init__(self, field: Field | None = None, params=bn):
        self.params = params
        self.F = field or Field(params.P)
        self.xi = tuple(params.XI)
        if self.xi not in ((9, 1), (1, 1)):
            raise ValueError(f"unsupported Fp6 non-residue xi={self.xi}")
        # Frobenius constants gamma_j = xi^(j(p-1)/6) as Montgomery limb pairs
        self._gamma = [None] + [
            tuple(self.F.pack([g[0], g[1]])[:, i : i + 1] for i in range(2))
            for g in params._GAMMA[1:]
        ]

    # -- raw limb stacking (ONE carry-propagating Field call for many ops) --
    #
    # Every Field.add/sub pays a carry-lookahead + conditional-subtract; the
    # tower batches all independent adds/subs of a formula into one wide call
    # (same "batch stacking" discipline as the muls, ops/fp.py). This is what
    # keeps both XLA graph size (compile time) and Pallas launch count low.

    @staticmethod
    def _cat(xs):
        return jnp.concatenate(xs, axis=1)

    @staticmethod
    def _split(x, k):
        b = x.shape[1] // k
        return [x[:, i * b : (i + 1) * b] for i in range(k)]

    def _add_n(self, lhs, rhs):
        """[(a_i + b_i)] for equal-width limb arrays — one Field.add."""
        if len(lhs) == 1:
            return [self.F.add(lhs[0], rhs[0])]
        return self._split(self.F.add(self._cat(lhs), self._cat(rhs)), len(lhs))

    def _sub_n(self, lhs, rhs, blog=None):
        if len(lhs) == 1:
            return [self.F.sub(lhs[0], rhs[0], blog)]
        return self._split(
            self.F.sub(self._cat(lhs), self._cat(rhs), blog), len(lhs)
        )

    # -- Fp2 ---------------------------------------------------------------

    def f2_add(self, a, b):
        c = self.F.add(self._cat([a[0], a[1]]), self._cat([b[0], b[1]]))
        c0, c1 = self._split(c, 2)
        return (c0, c1)

    def f2_sub(self, a, b, blog=None):
        c = self.F.sub(self._cat([a[0], a[1]]), self._cat([b[0], b[1]]), blog)
        c0, c1 = self._split(c, 2)
        return (c0, c1)

    def f2_neg(self, a, blog=None):
        z = self._cat([a[0], a[1]])
        c0, c1 = self._split(self.F.sub(jnp.zeros_like(z), z, blog), 2)
        return (c0, c1)

    def f2_conj(self, a, blog=None):
        return (a[0], self.F.neg(a[1], blog))

    def f2_add_many(self, pairs):
        """[(a+b)] for a list of Fp2 pairs — one Field.add total."""
        out = self._add_n(
            [p[0][0] for p in pairs] + [p[0][1] for p in pairs],
            [p[1][0] for p in pairs] + [p[1][1] for p in pairs],
        )
        k = len(pairs)
        return [(out[i], out[k + i]) for i in range(k)]

    def f2_sub_many(self, pairs, blog=None):
        out = self._sub_n(
            [p[0][0] for p in pairs] + [p[0][1] for p in pairs],
            [p[1][0] for p in pairs] + [p[1][1] for p in pairs],
            blog,
        )
        k = len(pairs)
        return [(out[i], out[k + i]) for i in range(k)]

    def f2_mul(self, a, b):
        """Karatsuba: 3 base muls in one stacked call.
        (a0+a1 i)(b0+b1 i) = (a0b0 - a1b1) + ((a0+a1)(b0+b1) - a0b0 - a1b1) i

        Resident bounds: products out <= 2^6*p, so the subtrahends (v1, v0
        and then v1) sit at blog=6 and the outputs land at (c0 <= 2^7*p,
        c1 <= 2^8*p). Operand constraint: la + lb <= 54.
        """
        F = self.F
        s = F.add(self._cat([a[0], b[0]]), self._cat([a[1], b[1]]))
        sa, sb = self._split(s, 2)  # a0+a1, b0+b1
        lhs = self._cat([a[0], a[1], sa])
        rhs = self._cat([b[0], b[1], sb])
        v0, v1, v2 = _split3(F.mul(lhs, rhs))
        d = F.sub(self._cat([v0, v2]), self._cat([v1, v0]), 6)
        c0, t = self._split(d, 2)
        c1 = F.sub(t, v1, 6)
        return (c0, c1)

    def f2_sqr(self, a):
        """(a0+a1 i)^2 = (a0+a1)(a0-a1) + 2 a0 a1 i — 2 base muls.

        Resident bounds: the internal a0 - a1 uses the universal blog=24
        offset (every tower call site keeps coordinates <= 2^24*p; input
        constraint la <= 24 so (la+1) + 25 stays inside RES_MUL_LOG2). Out
        (c0 <= 2^6*p, c1 <= 2^7*p)."""
        F = self.F
        m = F.add(a[0], a[1])
        s = F.sub(a[0], a[1], 24)
        prod = F.mul(self._cat([m, a[0]]), self._cat([s, a[1]]))
        c0, t = self._split(prod, 2)
        return (c0, F.add(t, t))

    def f2_sqr_many(self, elems):
        """Square a list of Fp2 elements in ONE stacked f2_sqr call."""
        k = len(elems)
        e = (
            self._cat([x[0] for x in elems]),
            self._cat([x[1] for x in elems]),
        )
        s = self.f2_sqr(e)
        return list(zip(self._split(s[0], k), self._split(s[1], k)))

    def f2_mul_fp(self, a, s):
        """Fp2 element times a base-field element (2 base muls, stacked)."""
        F = self.F
        prod = F.mul(self._cat([a[0], a[1]]), self._cat([s, s]))
        c0, c1 = self._split(prod, 2)
        return (c0, c1)

    def _x9(self, z):
        """9*z by add chain on an arbitrary-width limb array (4 adds)."""
        F = self.F
        z2 = F.add(z, z)
        z4 = F.add(z2, z2)
        z8 = F.add(z4, z4)
        return F.add(z8, z)

    def f2_mul_xi(self, a, blog=None):
        """Multiply by the Fp6 non-residue via add chains (no base mul).
        xi = 9+i (BN254): (9a0 - a1, 9a1 + a0), one stacked x9 chain;
        xi = 1+i (BLS12-381): (a0 - a1, a0 + a1).

        Resident: `blog` is the INPUT bound exponent (the subtrahend is an
        input coordinate); output bound la + 5 for xi = 9+i (the x9 chain
        adds 4, the sub 1), la + 1 for xi = 1+i."""
        F = self.F
        if self.xi == (1, 1):
            return (F.sub(a[0], a[1], blog), F.add(a[0], a[1]))
        n9 = self._x9(self._cat([a[0], a[1]]))
        n90, n91 = self._split(n9, 2)
        return (F.sub(n90, a[1], blog), F.add(n91, a[0]))

    def f2_mul_xi_many(self, elems, blog=None):
        """xi * e for a list of Fp2 elements — one stacked chain. `blog`
        bounds the WIDEST input element (resident mode)."""
        k = len(elems)
        c0s = self._cat([e[0] for e in elems])
        c1s = self._cat([e[1] for e in elems])
        if self.xi == (1, 1):
            d = self.F.sub(c0s, c1s, blog)
            s = self.F.add(c0s, c1s)
            return list(zip(self._split(d, k), self._split(s, k)))
        n9 = self._x9(self._cat([c0s, c1s]))
        parts = self._split(n9, 2 * k)
        d = self.F.sub(self._cat(parts[:k]), c1s, blog)
        s = self.F.add(self._cat(parts[k:]), c0s)
        return list(zip(self._split(d, k), self._split(s, k)))

    def f2_inv(self, a):
        """1/(a0+a1 i) = (a0 - a1 i)/(a0^2+a1^2).

        Resident: den <= 2^7*p feeds the Fermat chain (bounds stay <= 2^7*p
        throughout — see ResidentRns.pow_const); products cap at 2^6*p, so
        the final negation's subtrahend sits at blog=6."""
        F = self.F
        den = F.add(F.mul(a[0], a[0]), F.mul(a[1], a[1]))
        inv = F.inv(den)
        return (F.mul(a[0], inv), F.neg(F.mul(a[1], inv), 6))

    def f2_select(self, mask, a, b):
        return (self.F.select(mask, a[0], b[0]), self.F.select(mask, a[1], b[1]))

    def f2_eq(self, a, b):
        return self.F.eq(a[0], b[0]) & self.F.eq(a[1], b[1])

    def f2_is_zero(self, a):
        return self.F.is_zero(a[0]) & self.F.is_zero(a[1])

    def f2_zero(self, batch: int):
        # F.limb_dtype keeps lax.scan carries dtype-consistent across
        # representations (uint32 positional limbs, int32 residue rows)
        z = jnp.zeros((self.F.nlimbs, batch), self.F.limb_dtype)
        return (z, z)

    def f2_one(self, batch: int):
        return (
            self.F.constant(1, batch),
            jnp.zeros((self.F.nlimbs, batch), self.F.limb_dtype),
        )

    def f2_constant(self, c, batch: int):
        """Embed a bn254_ref Fp2 value (int pair) as broadcast limbs."""
        return (
            jnp.broadcast_to(self.F.pack([c[0]]), (self.F.nlimbs, batch)),
            jnp.broadcast_to(self.F.pack([c[1]]), (self.F.nlimbs, batch)),
        )

    # -- Fp2 stacking helpers ----------------------------------------------

    def _f2_stack(self, elems):
        """Concatenate Fp2 elements along the batch axis."""
        return (
            jnp.concatenate([e[0] for e in elems], axis=1),
            jnp.concatenate([e[1] for e in elems], axis=1),
        )

    def _f2_unstack(self, e, k):
        b = e[0].shape[1] // k
        return [
            (e[0][:, i * b : (i + 1) * b], e[1][:, i * b : (i + 1) * b])
            for i in range(k)
        ]

    # -- Fp6 ---------------------------------------------------------------

    def f6_add(self, a, b):
        out = self.f2_add_many(list(zip(a, b)))
        return tuple(out)

    def f6_sub(self, a, b, blog=None):
        out = self.f2_sub_many(list(zip(a, b)), blog)
        return tuple(out)

    def f6_neg(self, a, blog=None):
        z = self._cat([a[i][j] for i in range(3) for j in range(2)])
        parts = self._split(self.F.sub(jnp.zeros_like(z), z, blog), 6)
        return ((parts[0], parts[1]), (parts[2], parts[3]), (parts[4], parts[5]))

    def f6_mul(self, a, b):
        """Toom/Karatsuba: 6 Fp2 muls in ONE stacked f2_mul call
        (bn254_ref.f6_mul structure); all interpolation adds/subs stacked."""
        a0, a1, a2 = a
        b0, b1, b2 = b
        # the six pre-mul sums in one add call
        s = self.f2_add_many(
            [(a1, a2), (a0, a1), (a0, a2), (b1, b2), (b0, b1), (b0, b2)]
        )
        lhs = self._f2_stack([a0, a1, a2, s[0], s[1], s[2]])
        rhs = self._f2_stack([b0, b1, b2, s[3], s[4], s[5]])
        t0, t1, t2, u0, u1, u2 = self._f2_unstack(self.f2_mul(lhs, rhs), 6)
        # pairwise t-sums, then u - sums, in one call each. Resident bounds
        # (operand constraint max(la, lb) <= 26): products t, u <= 2^8*p,
        # w <= 2^9*p, d <= 2^10*p, xi-folds <= 2^15*p, out <= 2^16*p.
        w = self.f2_add_many([(t1, t2), (t0, t1), (t0, t2)])
        d0, d1, d2 = self.f2_sub_many([(u0, w[0]), (u1, w[1]), (u2, w[2])], 9)
        x0, x2 = self.f2_mul_xi_many([d0, t2], 10)  # xi*(u0-t1-t2), xi*t2
        c0, c1, c2 = self.f2_add_many([(t0, x0), (d1, x2), (d2, t1)])
        return (c0, c1, c2)

    def f6_mul_v(self, a, blog=None):
        """(c0,c1,c2) * v = (xi*c2, c0, c1). `blog` bounds a[2] (resident)."""
        return (self.f2_mul_xi(a[2], blog), a[0], a[1])

    def f6_inv(self, a):
        """bn254_ref.f6_inv structure. Resident bound walk (input <= 2^22*p,
        the f12_inv feed): squares <= 2^7*p, products <= 2^8*p, xi-folds
        <= 2^13*p, so t0 <= 2^14*p, t1 <= 2^13*p, t2 <= 2^9*p, den <=
        2^15*p — every product constraint inside RES_MUL_LOG2."""
        a0, a1, a2 = a
        t0 = self.f2_sub(
            self.f2_sqr(a0), self.f2_mul_xi(self.f2_mul(a1, a2), 8), 13
        )
        t1 = self.f2_sub(
            self.f2_mul_xi(self.f2_sqr(a2), 7), self.f2_mul(a0, a1), 8
        )
        t2 = self.f2_sub(self.f2_sqr(a1), self.f2_mul(a0, a2), 8)
        den = self.f2_add(
            self.f2_mul(a0, t0),
            self.f2_mul_xi(
                self.f2_add(self.f2_mul(a2, t1), self.f2_mul(a1, t2)), 9
            ),
        )
        inv = self.f2_inv(den)
        return (self.f2_mul(t0, inv), self.f2_mul(t1, inv), self.f2_mul(t2, inv))

    def f6_zero(self, batch):
        return (self.f2_zero(batch),) * 3

    def f6_one(self, batch):
        return (self.f2_one(batch), self.f2_zero(batch), self.f2_zero(batch))

    def f6_select(self, mask, a, b):
        return tuple(self.f2_select(mask, x, y) for x, y in zip(a, b))

    # -- Fp12 --------------------------------------------------------------

    def f12_mul(self, a, b):
        """Karatsuba over Fp6: 3 Fp6 muls -> one stacked f6_mul (54x batch);
        the six karatsuba input sums in one add call.

        Resident bounds (operand constraint max coords <= 2^25*p): f6_mul
        outputs v <= 2^16*p, so c0 <= 2^22*p and c1 <= 2^18*p — i.e.
        f12_mul(f, g) with coords <= 2^22*p lands back at <= 2^22*p, the
        stable fixed point the Miller/final-exp accumulators live at."""
        a0, a1 = a
        b0, b1 = b
        s = self.f2_add_many(
            [(a0[i], a1[i]) for i in range(3)] + [(b0[i], b1[i]) for i in range(3)]
        )
        lhs = tuple(self._f2_stack([a0[i], a1[i], s[i]]) for i in range(3))
        rhs = tuple(self._f2_stack([b0[i], b1[i], s[3 + i]]) for i in range(3))
        prod = self.f6_mul(lhs, rhs)
        v0, v1, v2 = zip(*(self._f2_unstack(c, 3) for c in prod))
        v0, v1, v2 = tuple(v0), tuple(v1), tuple(v2)
        c0 = self.f6_add(v0, self.f6_mul_v(v1, 16))
        # c1 = v2 - v0 - v1: six components, two stacked sub calls
        d = self.f2_sub_many(list(zip(v2, v0)), 16)
        c1 = tuple(self.f2_sub_many(list(zip(d, v1)), 16))
        return (c0, tuple(c1))

    def f12_sqr(self, a):
        return self.f12_mul(a, a)

    def f12_cyclo_sqr(self, a):
        """Squaring for elements of the cyclotomic subgroup G_{Phi6}(Fp2)
        (Granger–Scott 2010) — valid ONLY after the easy part of the final
        exponentiation has mapped the Miller value into that subgroup.

        With f = (x0 + x1 v + x2 v^2) + (x3 + x4 v + x5 v^2) w, the three
        Fp4 = Fp2[w^3]-subalgebra pairs (x0,x4), (x3,x2), (x1,x5) square
        independently, and the Phi6 norm relation recovers f^2 from those
        squares alone:

          a_j = xi*hi_j^2 + lo_j^2,  b_j = 2*lo_j*hi_j   (per Fp4 pair)
          C0 coords: 3*a_j - 2*x_j ;  C1 coords: 3*b'_j + 2*x_j

        Cost: 9 Fp2 squarings — all fused into ONE width-9B f2_sqr launch
        (= 18 base muls) vs the generic f12_sqr's 54. The 2ab terms come from
        (lo+hi)^2 - lo^2 - hi^2 so no extra multiply is spent on them.
        """
        if getattr(self.F, "is_resident", False):
            # reset the accumulator's bound before squaring: the cyclo
            # formula subtracts INPUT coordinates from derived terms, so it
            # converges only from a small input bound. One stacked refresh
            # (12 coords wide) drops any bound <= RES_MUL_LOG2 to <= 2^6*p
            # without leaving the residue domain; bound walk proceeds from
            # there to an output <= 2^18*p.
            a = self._f12_refresh(a)
        x0, x1, x2 = a[0]
        x3, x4, x5 = a[1]
        s40, s23, s51 = self.f2_add_many([(x4, x0), (x2, x3), (x5, x1)])
        q4, q0, q40, q2, q3, q23, q5, q1, q51 = self.f2_sqr_many(
            [x4, x0, s40, x2, x3, s23, x5, x1, s51]
        )
        # cross terms 2*x4*x0, 2*x2*x3, 2*x5*x1
        d = self.f2_sub_many([(q40, q4), (q23, q2), (q51, q5)], 7)
        t6, t7, t8 = self.f2_sub_many([(d[0], q0), (d[1], q3), (d[2], q1)], 7)
        # xi-folded Fp4 squares (one xi add-chain for all four)
        xt8, xt4, xt2, xt5 = self.f2_mul_xi_many([t8, q4, q2, q5], 9)
        u0, u1, u2 = self.f2_add_many([(xt4, q0), (xt2, q3), (xt5, q1)])
        # z = 3u - 2x (C0) / 3t + 2x (C1), via (u -/+ x) doubled + u
        w = self.f2_sub_many([(u0, x0), (u1, x1), (u2, x2)], 6)
        w += self.f2_add_many([(xt8, x3), (t6, x4), (t7, x5)])
        w2 = self.f2_add_many([(t, t) for t in w])
        z = self.f2_add_many(
            list(zip(w2, (u0, u1, u2, xt8, t6, t7)))
        )
        return ((z[0], z[1], z[2]), (z[3], z[4], z[5]))

    def f12_add(self, a, b):
        return (self.f6_add(a[0], b[0]), self.f6_add(a[1], b[1]))

    def f12_conj(self, a, blog=None):
        return (a[0], self.f6_neg(a[1], blog))

    def f12_inv(self, a):
        """Resident bounds (input <= 2^22*p): f6 squares <= 2^16*p, the
        mul_v fold <= 2^21*p, f6_inv input <= 2^22*p, output products <=
        2^16*p."""
        den = self.f6_inv(
            self.f6_sub(
                self._f6_sqr_via_mul(a[0]),
                self.f6_mul_v(self._f6_sqr_via_mul(a[1]), 16),
                21,
            )
        )
        return (
            self.f6_mul(a[0], den),
            self.f6_neg(self.f6_mul(a[1], den), 16),
        )

    def _f6_sqr_via_mul(self, a):
        return self.f6_mul(a, a)

    def f12_zero(self, batch):
        return (self.f6_zero(batch), self.f6_zero(batch))

    def f12_one(self, batch):
        return (self.f6_one(batch), self.f6_zero(batch))

    def f12_select(self, mask, a, b):
        return (
            self.f6_select(mask, a[0], b[0]),
            self.f6_select(mask, a[1], b[1]),
        )

    def f12_eq(self, a, b):
        out = None
        for x, y in zip(self._flatten12(a), self._flatten12(b)):
            e = self.F.eq(x, y)
            out = e if out is None else (out & e)
        return out

    def _flatten12(self, a):
        return [a[i][j][k] for i in range(2) for j in range(3) for k in range(2)]

    def _f12_refresh(self, a):
        """Resident-only: reset all 12 coordinate bounds to <= 2^6*p in ONE
        stacked refresh (a single mul_resident by the Montgomery one at 12x
        batch width — same batch-stacking discipline as the muls)."""
        parts = self._split(self.F.refresh(self._cat(self._flatten12(a))), 12)
        return (
            ((parts[0], parts[1]), (parts[2], parts[3]), (parts[4], parts[5])),
            ((parts[6], parts[7]), (parts[8], parts[9]), (parts[10], parts[11])),
        )

    def as_resident(self) -> "Tower":
        """A Tower over the resident form of this tower's RNS field: same
        formulas, values stay joint-residue arrays end to end (CRT deferred
        to the caller's genuine boundaries). Gammas and embedded constants
        re-pack through the adapter at construction. Cached."""
        if not hasattr(self.F, "resident"):
            raise TypeError(
                f"as_resident() needs the 'rns' field backend; this tower's "
                f"field is {self.F.backend!r}"
            )
        cached = getattr(self, "_resident_tower", None)
        if cached is None:
            cached = Tower(self.F.resident(), params=self.params)
            self._resident_tower = cached
        return cached

    def f12_frobenius(self, a):
        """x -> x^p (bn254_ref.f12_frobenius structure: conjugate each Fp2
        coordinate, multiply w-degree-j slots by gamma_j). All six
        conjugations in one stacked neg; the 5 gamma muls in one f2_mul."""
        (c00, c01, c02), (c10, c11, c12) = a
        batch = c00[0].shape[1]
        coords = [c00, c01, c02, c10, c11, c12]
        z = self._cat([c[1] for c in coords])
        # resident: every Frobenius call site (final exp) holds coords at
        # the <= 2^22*p accumulator fixed point — blog=22 covers them all
        negs = self._split(self.F.sub(jnp.zeros_like(z), z, 22), 6)
        conj = [(coords[i][0], negs[i]) for i in range(6)]

        def g(j):
            g0, g1 = self._gamma[j]
            return (
                jnp.broadcast_to(g0, (self.F.nlimbs, batch)),
                jnp.broadcast_to(g1, (self.F.nlimbs, batch)),
            )

        if getattr(self.F, "is_resident", False):
            # multiply the w^0 slot by one as well (6-wide instead of
            # 5-wide — same single f2_mul launch) so EVERY output slot is a
            # product with its bound reset to <= 2^8*p; leaving the slot as
            # a raw conjugate would let bounds accumulate across chained
            # Frobenius applications (fp3 = frobenius^3 in the final exp)
            lhs = self._f2_stack(conj)
            rhs = self._f2_stack([self.f2_one(batch), g(2), g(4), g(1), g(3), g(5)])
            m00, m01, m02, m10, m11, m12 = self._f2_unstack(
                self.f2_mul(lhs, rhs), 6
            )
            return ((m00, m01, m02), (m10, m11, m12))
        lhs = self._f2_stack(conj[1:])
        rhs = self._f2_stack([g(2), g(4), g(1), g(3), g(5)])
        m01, m02, m10, m11, m12 = self._f2_unstack(self.f2_mul(lhs, rhs), 5)
        return ((conj[0], m01, m02), (m10, m11, m12))

    def f12_frobenius2(self, a):
        return self.f12_frobenius(self.f12_frobenius(a))

    def f12_pow_const(
        self,
        a,
        e: int,
        cyclo: bool = False,
        unroll: bool = False,
        window: int | None = None,
    ):
        """a^e for a fixed public exponent. cyclo=True uses the 3x-cheaper
        cyclotomic squaring — only valid when a lives in the cyclotomic
        subgroup (final exp).

        Two lowerings, same algebra:
          * scan (default): square + selected multiply per bit — keeps the
            traced graph ~60x smaller than unrolling, which matters for XLA
            compile times (task spec: compiler-friendly control flow).
          * unroll: python loop over the statically-known bits, emitting the
            multiply ONLY on 1-bits, at a graph that grows with bits(e). No
            production caller opts in — this environment's compilers cannot
            absorb pairing-sized unrolled graphs (BN254Pairing.__init__
            note) — but the lowering is kept, tested at small exponents, for
            co-located deployments whose compiler can.

        `window` pins the scan's digit width (1 = plain bit scan, 4 = the
        accelerator table+gather form); None defers to default_pow_window so
        tests can oracle-check both lowerings on any backend."""
        import jax

        from handel_tpu.ops.fp import default_pow_window, windowed_pow

        sqr = self.f12_cyclo_sqr if cyclo else self.f12_sqr
        if unroll:
            # static bit chain: only the 1-bit multiplies are emitted. The
            # graph grows with bits(e); fine for the small exponents the
            # flag is tested with, and an option for co-located deployments
            # whose compiler absorbs large graphs (this environment's remote
            # compile helper cannot — see BN254Pairing docstring note)
            acc = a
            for c in bin(e)[3:]:
                acc = sqr(acc)
                if c == "1":
                    acc = self.f12_mul(acc, a)
            return acc

        # windowed digit scan on accelerators — for the 63-bit BN U: 29
        # executed f12_muls per chain instead of the bit-scan's 62, same
        # squaring count; plain bit scan on CPU (default_pow_window: the
        # per-site table+gather is a compile-time tax the CPU gate can't pay)
        return windowed_pow(
            a,
            e,
            default_pow_window() if window is None else window,
            mul=self.f12_mul,
            sqr=sqr,
            stack=lambda t: jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *t
            ),
            take=lambda s, i: jax.tree_util.tree_map(lambda x: x[i], s),
            select=lambda c, x, y: self.f12_select(
                jnp.broadcast_to(c, x[0][0][0].shape[1:]), x, y
            ),
        )

    def f12_pow_u(self, a, cyclo: bool = False, unroll: bool = False):
        """a^U for the BN parameter U (BN254 tower only).

        BLS parameter sets define no U (they expose X instead and override
        final_exp entirely), so fail loudly rather than with an opaque
        AttributeError mid-trace."""
        U = getattr(self.params, "U", None)
        if U is None:
            raise TypeError(
                f"f12_pow_u needs a BN parameter set with U; "
                f"{type(self.params).__name__} has none (BLS towers use "
                f"their own final-exp chain)"
            )
        return self.f12_pow_const(a, U, cyclo=cyclo, unroll=unroll)

    # -- host conversions ---------------------------------------------------

    def f2_pack(self, vals):
        """List of bn254_ref Fp2 values -> batched limb Fp2."""
        return (
            self.F.pack([v[0] for v in vals]),
            self.F.pack([v[1] for v in vals]),
        )

    def f2_unpack(self, a):
        c0 = self.F.unpack(a[0])
        c1 = self.F.unpack(a[1])
        return list(zip(c0, c1))

    def f12_pack(self, vals):
        """List of bn254_ref Fp12 values -> batched limb Fp12."""
        return tuple(
            tuple(
                self.f2_pack([v[i][j] for v in vals]) for j in range(3)
            )
            for i in range(2)
        )

    def f12_unpack(self, a):
        flat = [self.f2_unpack(a[i][j]) for i in range(2) for j in range(3)]
        batch = len(flat[0])
        return [
            (
                (flat[0][k], flat[1][k], flat[2][k]),
                (flat[3][k], flat[4][k], flat[5][k]),
            )
            for k in range(batch)
        ]

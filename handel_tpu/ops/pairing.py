"""Batched JAX optimal-ate pairing for BN254 — the device verification engine.

This is the kernel the whole project exists for: it replaces the reference's
native pairing (`bn256.Pair` at bn256/cf/bn256.go:92-93, used by
`VerifySignature` at :86-98) with a *batched* product-of-pairings check that
verifies a whole queue of Handel candidates in one launch
(processing.go:342-368 becomes `models/bn254_jax.py:batch_verify`).

Structure (scalar oracle: ops/bn254_ref.py `miller_loop_projective` /
`final_exponentiation`, validated bit-exactly against it):

  * **Inversion-free Miller loop.** The accumulator point T runs in
    homogeneous projective coordinates on the twist E'(Fp2); each step emits a
    sparse line with Fp2 coefficients in the (1, w, w^3) slots. All scale
    factors live in Fp2 and die in the easy part of the final exponentiation.
  * **lax.scan over the 64 static bits** of 6u+2 (MSB-first, top bit
    consumed by the loop init). Every step computes both the doubling and the
    mixed addition and selects by the (statically known, per-step scalar) bit
    — fixed trip count, no data-dependent control flow, and a traced graph
    ~64x smaller than full unrolling (XLA compile-time matters).
  * **Lane semantics.** Everything is batch-last limb arrays ((nlimbs, B)
    leaves, ops/fp.py layout); one Miller step is a handful of stacked
    `Field.mul` calls (ops/tower.py "batch stacking"), so the Pallas
    mont-mul kernel sees full lanes even at small candidate counts.
  * **Masked lanes.** A (B,) validity mask selects f = 1 for lanes holding
    infinity points or padding, making the product check ignore them — the
    device analogue of the reference's nil-checks (bn256/go/bn256.go:86-94).
  * **Shared final exponentiation.** `pairing_check` multiplies the Miller
    values of each candidate's pairs first and runs ONE final exponentiation
    on the product — the structural win over the reference's two-full-pairings
    compare per signature.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from handel_tpu.ops import bls12_381_ref as bls
from handel_tpu.ops import bn254_ref as bn
from handel_tpu.ops.curve import BLS12Curves, BN254Curves
from handel_tpu.ops.fp import Field
from handel_tpu.ops.tower import Tower

# MSB-first bits of the ate loop count 6u+2, top bit dropped (consumed by the
# Miller-loop initialization T = Q, f = 1).
_ATE_BITS = [int(c) for c in bin(bn.ATE_LOOP_COUNT)[3:]]


class BN254Pairing:
    """Batched optimal-ate pairing over the shared Field/Tower/Curves stack."""

    def __init__(self, curves: BN254Curves | None = None,
                 resident: bool | None = None):
        self.curves = curves or self._default_curves()
        self.F: Field = self.curves.F
        self.T: Tower = self.curves.T
        # Residue-resident mode (rns backend): the Miller loop and final
        # exponentiation run entirely on joint-residue values — positional
        # limbs appear only at genuine boundaries (point coordinates in,
        # GT verdict/element out). None = auto: on exactly when the field
        # backend is 'rns'.
        if resident is None:
            resident = self.F.backend == "rns"
        elif resident and self.F.backend != "rns":
            raise ValueError(
                f"resident pairing needs the 'rns' field backend (got "
                f"{self.F.backend!r}): construct the curve stack with "
                f"backend='rns' / fp_backend = \"rns\", or pass "
                f"resident=False"
            )
        self.resident = resident
        # every internal tower call routes through _Tw; the public entry
        # points convert at the boundaries when _Tw is the resident tower
        self._Tw: Tower = self.T.as_resident() if resident else self.T
        # Note on static unrolling: emitting the Miller loop's 64 steps as
        # straight-line code (skipping the ~39 0-bit add branches the scan
        # computes and discards) was measured and REJECTED — the ~60x-larger
        # graph OOM-kills both the XLA CPU compiler (128 GB RSS) and this
        # environment's remote TPU compile helper (13.5 MB MLIR -> SIGKILL).
        # The windowed pow chains (Tower.f12_pow_const, w=4) capture the
        # same class of savings for the final exponentiation in scan-sized
        # graphs instead.
        # psi-Frobenius constants for the ate correction points
        # (bn254_ref.miller_loop_projective: gamma_2 for x, gamma_3 for y)
        self._g2c = self.curves.params._GAMMA[2]
        self._g3c = self.curves.params._GAMMA[3]

    @classmethod
    def _default_curves(cls):
        return BN254Curves()

    # -- small helpers -------------------------------------------------------

    def _mm(self, pairs):
        """Stack independent Fp2 multiplications into one f2_mul call."""
        T = self._Tw
        lhs = T._f2_stack([p[0] for p in pairs])
        rhs = T._f2_stack([p[1] for p in pairs])
        return T._f2_unstack(T.f2_mul(lhs, rhs), len(pairs))

    def _points_in(self, p, q):
        """Boundary conversion IN: the six point-coordinate arrays (G1 x, y
        and the two Fp2 G2 coordinates) residue-convert in ONE stacked
        to_resident — this plus the verdict/element conversion out is the
        entire positional surface of a resident pairing. No-op when the
        pairing runs positionally."""
        xp, yp = p
        xq, yq = q
        if not self.resident:
            return p, q
        F = self.F
        cat = jnp.concatenate([xp, yp, xq[0], xq[1], yq[0], yq[1]], axis=1)
        b = xp.shape[1]
        r = F.to_resident(cat)
        parts = [r[:, i * b : (i + 1) * b] for i in range(6)]
        return (parts[0], parts[1]), (
            (parts[2], parts[3]),
            (parts[4], parts[5]),
        )

    def _f12_out(self, f):
        """Boundary conversion OUT: a resident Fp12 element reconstructs to
        canonical positional limbs in ONE stacked from_resident (12 coords
        wide). Passthrough when positional."""
        if not self.resident:
            return f
        T, F = self.T, self.F
        flat = self._Tw._flatten12(f)
        b = flat[0].shape[1]
        v = F.from_resident(jnp.concatenate(flat, axis=1))
        parts = [v[:, i * b : (i + 1) * b] for i in range(12)]
        return (
            ((parts[0], parts[1]), (parts[2], parts[3]), (parts[4], parts[5])),
            ((parts[6], parts[7]), (parts[8], parts[9]), (parts[10], parts[11])),
        )

    @staticmethod
    def _dbl_n(T, a, k: int):
        """a * 2^k by repeated addition (cheap, no field mul)."""
        for _ in range(k):
            a = T.f2_add(a, a)
        return a

    def _line_f12(self, line, batch):
        """Sparse line -> full Fp12 element. The step formulas emit
        (yp-term, xp-term, constant); the D-twist untwist puts them at
        w-degree slots 0, 1, 3 (w^3 = v*w).

        (Kept as a full element so the accumulator update is the single
        stacked f12_mul launch; a 15-mul sparse multiply saves ~17% arithmetic
        but triples the kernel-launch count — measured slower.)
        """
        c_yp, c_xp, c_const = line
        z = self._Tw.f2_zero(batch)
        return ((c_yp, z, z), (c_xp, c_const, z))

    # -- Miller-loop steps (bn254_ref.miller_loop_projective dbl/add) --------

    def _dbl_step(self, Tpt, xp, yp):
        """Doubling step: new T and the tangent line at T evaluated at
        P = (xp, yp). Line scaled by 2YZ^3 (killed by final exp)."""
        Tw = self._Tw
        X, Y, Z = Tpt
        # Resident bound walk (T invariant X <= 2^8*p, Y <= 2^12*p,
        # Z <= 2^8*p; xp/yp enter at bound 0): every product lands <= 2^8*p
        # (f2_mul), so the blog literals below are the derived-subtrahend
        # bounds — the full table is in HACKING.md "Residue-resident
        # pairing". Output T3 = (<=8, <=12, <=8) re-establishes the
        # invariant; line coefficients <= 2^10*p.
        XX, YY, YZ = self._mm([(X, X), (Y, Y), (Y, Z)])
        n = Tw.f2_add(Tw.f2_add(XX, XX), XX)  # 3X^2
        d = Tw.f2_add(YZ, YZ)  # 2YZ
        nn, dd, YYZ, YZZ, nZ, nX = self._mm(
            [(n, n), (d, d), (YY, Z), (YZ, Z), (n, Z), (n, X)]
        )
        XYYZ, ddd = self._mm([(X, YYZ), (dd, d)])
        e = Tw.f2_sub(nn, self._dbl_n(Tw, XYYZ, 3), 11)  # n^2 - 8XY^2Z
        # 12*XYYZ = 8*XYYZ + 4*XYYZ by add chains
        XYYZ12 = Tw.f2_add(self._dbl_n(Tw, XYYZ, 3), self._dbl_n(Tw, XYYZ, 2))
        # line coefficients; xp/yp are base-field: embed as (x, 0) Fp2
        zero = jnp.zeros_like(xp)
        X3, t, YYZ2, c0, cw = self._mm(
            [
                (e, d),
                (n, Tw.f2_sub(XYYZ12, nn, 8)),  # n*(12XY^2Z - n^2)
                (YYZ, YYZ),  # (Y^2 Z)^2 = Y^4 Z^2
                (YZZ, (yp, zero)),
                (nZ, (xp, zero)),
            ]
        )
        Y3 = Tw.f2_sub(t, self._dbl_n(Tw, YYZ2, 3), 11)
        T3 = (X3, Y3, ddd)
        line = (
            Tw.f2_add(c0, c0),  # 2YZ^2 * yp
            Tw.f2_neg(cw, 8),  # -3X^2 Z * xp
            Tw.f2_sub(nX, Tw.f2_add(YYZ, YYZ), 9),  # 3X^3 - 2Y^2 Z
        )
        return T3, line

    def _add_step(self, Tpt, Q, xp, yp):
        """Mixed-addition step T + Q (Q affine) and the line through them
        evaluated at P. Line scaled by d = x2 Z - X."""
        Tw = self._Tw
        X, Y, Z = Tpt
        x2, y2 = Q
        # Resident bounds: T at the (8, 12, 8) invariant, Q affine coords
        # <= 2^9*p (loop Q enters at 0; the psi-correction points of the BN
        # tail at <= 2^9*p) — n <= 2^13*p, d <= 2^9*p, every mul exponent
        # sum well under RES_MUL_LOG2; output T3 <= (8, 9, 8).
        y2Z, x2Z = self._mm([(y2, Z), (x2, Z)])
        n = Tw.f2_sub(y2Z, Y, 12)
        d = Tw.f2_sub(x2Z, X, 8)
        zero = jnp.zeros_like(xp)
        dd, nn, nx2, dy2, c0, cw = self._mm(
            [(d, d), (n, n), (n, x2), (d, y2), (d, (yp, zero)), (n, (xp, zero))]
        )
        nnZ, Xdd, ddd, x2Zdd = self._mm(
            [(nn, Z), (Tw.f2_add(X, x2Z), dd), (dd, d), (x2Z, dd)]
        )
        e = Tw.f2_sub(nnZ, Xdd, 8)
        X3, t, y2Zddd, Z3 = self._mm(
            [(e, d), (n, Tw.f2_sub(x2Zdd, e, 9)), (y2Z, ddd), (Z, ddd)]
        )
        Y3 = Tw.f2_sub(t, y2Zddd, 8)
        line = (c0, Tw.f2_neg(cw, 8), Tw.f2_sub(nx2, dy2, 8))
        return (X3, Y3, Z3), line

    # -- Miller loop ---------------------------------------------------------

    # loop bits for the shared scan (overridden per curve family)
    _LOOP_BITS = _ATE_BITS

    def miller_loop(self, p, q, mask=None):
        """Batched Miller loop: shared dbl/add scan over the family's static
        loop bits, then the family tail (`_miller_tail`).

        p: (xp, yp) base-field limb arrays (G1 affine), q: ((x...), (y...))
        Fp2 pairs (G2' affine), mask: optional (B,) bool — lanes with mask
        False (infinity/padding) return f = 1. Output: Fp12 batch
        (canonical positional limbs in either mode — resident runs convert
        at this public boundary)."""
        return self._f12_out(self._miller_loop_res(p, q, mask))

    def _miller_loop_res(self, p, q, mask=None):
        """`miller_loop` staying in the working representation (resident
        joint residues when self.resident) — the form `pairing` and
        `pairing_check` chain into the final exponentiation without an
        intermediate CRT reconstruction."""
        Tw = self._Tw
        p, q = self._points_in(p, q)
        xp, yp = p
        xq, yq = q
        batch = xp.shape[1]
        bits = jnp.asarray(self._LOOP_BITS, jnp.uint32)

        def step(carry, bit):
            Tpt, f = carry
            f = Tw.f12_sqr(f)
            Tpt, line = self._dbl_step(Tpt, xp, yp)
            f = Tw.f12_mul(f, self._line_f12(line, batch))
            Ta, line_a = self._add_step(Tpt, (xq, yq), xp, yp)
            fa = Tw.f12_mul(f, self._line_f12(line_a, batch))
            takes = jnp.broadcast_to(bit == 1, (batch,))
            Tpt = tuple(Tw.f2_select(takes, a, b) for a, b in zip(Ta, Tpt))
            f = Tw.f12_select(takes, fa, f)
            return (Tpt, f), None

        T0 = (xq, yq, Tw.f2_one(batch))
        (Tpt, f), _ = jax.lax.scan(step, (T0, Tw.f12_one(batch)), bits)
        f = self._miller_tail(Tpt, f, (xq, yq), xp, yp, batch)

        if mask is not None:
            f = Tw.f12_select(mask, f, Tw.f12_one(batch))
        return f

    def _miller_tail(self, Tpt, f, q, xp, yp, batch):
        """BN ate corrections: add psi(Q) and -psi^2(Q) on the twist
        (bn254_ref.miller_loop_projective tail). Resident: input points are
        bound-0 (canonical y < p makes the blog=0 conjugate nonnegative);
        the psi products land <= 2^8*p, so the correction points enter
        `_add_step` within its <= 2^9*p affine budget."""
        Tw = self._Tw
        xq, yq = q
        g2 = Tw.f2_constant(self._g2c, batch)
        g3 = Tw.f2_constant(self._g3c, batch)
        q1x, q1y = self._mm([(Tw.f2_conj(xq, 0), g2), (Tw.f2_conj(yq, 0), g3)])
        q2x, q2y = self._mm([(Tw.f2_conj(q1x, 8), g2), (Tw.f2_conj(q1y, 8), g3)])
        q2y = Tw.f2_neg(q2y, 8)  # q2 = -psi^2(Q)
        Tpt, line = self._add_step(Tpt, (q1x, q1y), xp, yp)
        f = Tw.f12_mul(f, self._line_f12(line, batch))
        _, line = self._add_step(Tpt, (q2x, q2y), xp, yp)
        return Tw.f12_mul(f, self._line_f12(line, batch))

    # -- final exponentiation ------------------------------------------------

    def final_exp(self, f):
        """f^((p^12-1)/r): easy part by conjugation/Frobenius + one Fp12
        inversion, hard part by the BN addition chain
        (bn254_ref.final_exponentiation, device form).

        Resident: runs entirely on joint residues (accumulators hold the
        f12_mul <= 2^22*p fixed point; conjugation sites pass blog=22,
        covering every input here)."""
        Tw = self._Tw
        # easy: f^(p^6-1) = conj(f) * f^-1, then ^(p^2+1)
        f = Tw.f12_mul(Tw.f12_conj(f, 22), Tw.f12_inv(f))
        f = Tw.f12_mul(Tw.f12_frobenius2(f), f)

        # hard part (Scott et al. chain; inversion = conjugation and squaring
        # = Granger-Scott cyclotomic squaring now that f is in the subgroup)
        fu = Tw.f12_pow_u(f, cyclo=True)
        fu2 = Tw.f12_pow_u(fu, cyclo=True)
        fu3 = Tw.f12_pow_u(fu2, cyclo=True)
        fp = Tw.f12_frobenius(f)
        fp2 = Tw.f12_frobenius(fp)
        fp3 = Tw.f12_frobenius(fp2)
        y0 = Tw.f12_mul(Tw.f12_mul(fp, fp2), fp3)
        y1 = Tw.f12_conj(f, 22)
        y2 = Tw.f12_frobenius2(fu2)
        y3 = Tw.f12_conj(Tw.f12_frobenius(fu), 22)
        y4 = Tw.f12_conj(Tw.f12_mul(fu, Tw.f12_frobenius(fu2)), 22)
        y5 = Tw.f12_conj(fu2, 22)
        y6 = Tw.f12_conj(Tw.f12_mul(fu3, Tw.f12_frobenius(fu3)), 22)

        t0 = Tw.f12_mul(Tw.f12_mul(Tw.f12_cyclo_sqr(y6), y4), y5)
        t1 = Tw.f12_mul(Tw.f12_mul(y3, y5), t0)
        t0 = Tw.f12_mul(t0, y2)
        t1 = Tw.f12_mul(Tw.f12_cyclo_sqr(t1), t0)
        t1 = Tw.f12_cyclo_sqr(t1)
        t0 = Tw.f12_mul(t1, y1)
        t1 = Tw.f12_mul(t1, y0)
        t0 = Tw.f12_cyclo_sqr(t0)
        return Tw.f12_mul(t0, t1)

    # -- top-level entry points ----------------------------------------------

    def pairing(self, p, q, mask=None):
        """Batched e(P, Q) -> GT; masked lanes give 1. Resident runs stay
        in the residue domain across Miller loop AND final exponentiation —
        one conversion in, one out."""
        return self._f12_out(self.final_exp(self._miller_loop_res(p, q, mask)))

    def gt_is_one(self, f):
        """(B,) bool: lane-wise comparison against the GT identity.

        Comparison is a positional boundary: a resident element (recognized
        by its joint-residue row count) reconstructs here — the verdict is
        the pairing check's single CRT exit."""
        if self.resident and f[0][0][0].shape[0] == self.F.k_all:
            f = self._f12_out(f)
        batch = f[0][0][0].shape[1]
        return self.T.f12_eq(f, self.T.f12_one(batch))

    def pairing_check(self, p, q, mask, groups: int):
        """Product-of-pairings verdicts for `groups` candidates.

        Pair-chunk-major batch layout: lane i*groups + j holds pair i of
        candidate j (total batch = pairs_per_candidate * groups). Computes
        prod_i e(P_ij, Q_ij) per candidate with ONE shared final
        exponentiation and returns (groups,) bools. Masked-out lanes
        contribute 1 to their candidate's product.

        Resident runs thread the residue form through the per-candidate
        accumulation and the shared final exponentiation; the only CRT
        reconstruction is the verdict comparison in `gt_is_one`.
        """
        f = self._miller_loop_res(p, q, mask)
        total = f[0][0][0].shape[1]
        per = total // groups

        def slice_chunk(i):
            return jax.tree_util.tree_map(
                lambda a: a[:, i * groups : (i + 1) * groups], f
            )

        acc = slice_chunk(0)
        for i in range(1, per):
            acc = self._Tw.f12_mul(acc, slice_chunk(i))
        return self.gt_is_one(self.final_exp(acc))


class BLS12Pairing(BN254Pairing):
    """Batched optimal-ate pairing for BLS12-381 (ops/bls12_381_ref.py).

    Shares the projective dbl/add step formulas and the scan machinery with
    the BN254 engine — the step outputs (yp-term, xp-term, constant) are
    family-independent; what changes is:

      * loop bits: |z| (z = -0xd201..., 63 bits, weight 6) with a final
        conjugation because z < 0 — no ate correction additions;
      * line slot placement: the M-type twist untwist puts the coefficients
        at w-degrees (0, 2, 3) = Fp12 slots a0[0], a0[1], a1[1], with the
        CONSTANT at w^0 (the D-twist puts the yp-term there);
      * final exponentiation: the BLS12 hard part
        (z-1)^2 (z+p) (z^2+p^2-1) + 3 — computing the cubed pairing, a
        standard bilinear substitute since gcd(3, r) = 1
        (bls12_381_ref.final_exponentiation).
    """

    _LOOP_BITS = [int(c) for c in bin(-bls.Z)[3:]]

    @classmethod
    def _default_curves(cls):
        return BLS12Curves()

    def _line_f12(self, line, batch):
        c_yp, c_xp, c_const = line
        z = self._Tw.f2_zero(batch)
        return ((c_const, c_xp, z), (z, c_yp, z))

    def _miller_tail(self, Tpt, f, q, xp, yp, batch):
        # z < 0: f_z = 1/f_{|z|} up to final exp -> conjugate (resident:
        # the scan accumulator sits at the <= 2^22*p fixed point)
        return self._Tw.f12_conj(f, 22)

    def _pow_z(self, x):
        """x^z in the cyclotomic subgroup (z < 0: pow |z|, then conjugate)."""
        return self._Tw.f12_conj(
            self._Tw.f12_pow_const(x, -bls.Z, cyclo=True), 22
        )

    def final_exp(self, f):
        """Easy part + BLS12 hard part via
        3(p^4-p^2+1)/r = (z-1)^2 (z+p) (z^2+p^2-1) + 3
        (bls12_381_ref.final_exponentiation, device form with cyclotomic
        squarings). Resident conj literals: the Miller tail's conjugation
        leaves f <= 2^23*p (hence blog=23 on the easy part); everything
        after the easy part returns to the <= 2^22*p mul fixed point."""
        Tw = self._Tw
        f = Tw.f12_mul(Tw.f12_conj(f, 23), Tw.f12_inv(f))  # f^(p^6-1)
        f = Tw.f12_mul(Tw.f12_frobenius2(f), f)  # ^(p^2+1)
        t0 = Tw.f12_mul(self._pow_z(f), Tw.f12_conj(f, 22))  # f^(z-1)
        t1 = Tw.f12_mul(self._pow_z(t0), Tw.f12_conj(t0, 22))  # f^((z-1)^2)
        g = Tw.f12_mul(self._pow_z(t1), Tw.f12_frobenius(t1))  # ^(z+p)
        gz2 = self._pow_z(self._pow_z(g))
        h = Tw.f12_mul(Tw.f12_mul(gz2, Tw.f12_frobenius2(g)), Tw.f12_conj(g, 22))
        return Tw.f12_mul(h, Tw.f12_mul(Tw.f12_cyclo_sqr(f), f))  # * f^3

"""Pure-Python BN254 (alt_bn128): tower fields, curve groups, optimal ate pairing.

This is the framework's scalar ground truth — the role the imported
`cloudflare/bn256` library plays for the reference (bn256/cf/bn256.go:17): all
JAX/TPU kernels (ops/fp.py, ops/pairing.py) and the C++ native backend are
validated against this module, and it doubles as a (slow) host fallback scheme.

Curve: the SNARK-friendly BN curve used by cloudflare/bn256 and the Ethereum
precompiles ("alt_bn128"), parameter u = 4965661367192848881:
    p = 36u^4 + 36u^3 + 24u^2 + 6u + 1
    r = 36u^4 + 36u^3 + 18u^2 + 6u + 1
    E(Fp):  y^2 = x^3 + 3,           G1 generator (1, 2)
    E'(Fp2): y^2 = x^3 + 3/xi,       xi = 9 + i,  Fp2 = Fp[i]/(i^2+1)
Tower: Fp2 -> Fp6 = Fp2[v]/(v^3 - xi) -> Fp12 = Fp6[w]/(w^2 - v).

The pairing is the optimal ate pairing: Miller loop over 6u+2 with affine line
functions evaluated at G1 points lifted through the D-twist
psi(x', y') = (x' w^2, y' w^3), followed by the final exponentiation
(p^12 - 1)/r — both a naive pow (oracle) and the standard fast
Frobenius/addition-chain version that device kernels mirror.

Everything here is plain Python ints — clarity over speed.
"""

from __future__ import annotations

# -- curve constants --------------------------------------------------------

U = 4965661367192848881  # BN parameter
P = 36 * U**4 + 36 * U**3 + 24 * U**2 + 6 * U + 1  # field modulus
R = 36 * U**4 + 36 * U**3 + 18 * U**2 + 6 * U + 1  # group order
ATE_LOOP_COUNT = 6 * U + 2

assert P == 21888242871839275222246405745257275088696311157297823662689037894645226208583
assert R == 21888242871839275222246405745257275088548364400416034343698204186575808495617
assert P % 4 == 3 and P % 6 == 1

B = 3  # G1 curve coefficient

G1_GEN = (1, 2)

# E'(Fp2) generator (standard alt_bn128 G2 generator, as in EIP-197)
G2_GEN = (
    (
        10857046999023057135944570762232829481370756359578518086990519993285655852781,
        11559732032986387107991004021392285783925812861821192530917403151452391805634,
    ),
    (
        8495653923123431417604973247489272438418190587263600148770280649306958101930,
        4082367875863433681332203403145435568316851327593401208105741076214120093531,
    ),
)


# -- Fp2 = Fp[i]/(i^2 + 1): elements are (c0, c1) = c0 + c1*i ---------------


def f2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def f2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def f2_neg(a):
    return ((-a[0]) % P, (-a[1]) % P)


def f2_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    return ((a0 * b0 - a1 * b1) % P, (a0 * b1 + a1 * b0) % P)


def f2_sqr(a):
    a0, a1 = a
    return ((a0 + a1) * (a0 - a1) % P, 2 * a0 * a1 % P)


def f2_scalar(a, k):
    return (a[0] * k % P, a[1] * k % P)


def f2_conj(a):
    return (a[0], (-a[1]) % P)


def f2_inv(a):
    a0, a1 = a
    den = pow(a0 * a0 + a1 * a1, -1, P)
    return (a0 * den % P, (-a1) * den % P)


def f2_pow(a, e):
    result = F2_ONE
    base = a
    while e:
        if e & 1:
            result = f2_mul(result, base)
        base = f2_sqr(base)
        e >>= 1
    return result


def f2_sqrt(a):
    """Square root in Fp2 for p = 3 mod 4 (complex-extension algorithm);
    returns None when `a` is not a quadratic residue."""
    if a == F2_ZERO:
        return F2_ZERO
    a1 = f2_pow(a, (P - 3) // 4)
    alpha = f2_mul(f2_sqr(a1), a)  # a^((p-1)/2)
    x0 = f2_mul(a1, a)  # a^((p+1)/4)
    if alpha == ((-1) % P, 0):
        x = f2_mul((0, 1), x0)  # i * x0
    else:
        b = f2_pow(f2_add(F2_ONE, alpha), (P - 1) // 2)
        x = f2_mul(b, x0)
    return x if f2_sqr(x) == a else None


F2_ZERO = (0, 0)
F2_ONE = (1, 0)
XI = (9, 1)  # the Fp6 non-residue: v^3 = xi


def f2_mul_xi(a):
    """Multiply by xi = 9 + i."""
    a0, a1 = a
    return ((9 * a0 - a1) % P, (9 * a1 + a0) % P)


# -- Fp6 = Fp2[v]/(v^3 - xi): elements are (c0, c1, c2) ---------------------


def f6_add(a, b):
    return tuple(f2_add(x, y) for x, y in zip(a, b))


def f6_sub(a, b):
    return tuple(f2_sub(x, y) for x, y in zip(a, b))


def f6_neg(a):
    return tuple(f2_neg(x) for x in a)


def f6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = f2_mul(a0, b0)
    t1 = f2_mul(a1, b1)
    t2 = f2_mul(a2, b2)
    # Karatsuba/Toom-style interpolation
    c0 = f2_add(t0, f2_mul_xi(f2_sub(f2_mul(f2_add(a1, a2), f2_add(b1, b2)), f2_add(t1, t2))))
    c1 = f2_add(
        f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)), f2_add(t0, t1)),
        f2_mul_xi(t2),
    )
    c2 = f2_add(f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)), f2_add(t0, t2)), t1)
    return (c0, c1, c2)


def f6_sqr(a):
    return f6_mul(a, a)


def f6_mul_v(a):
    """Multiply by v: (c0, c1, c2) -> (xi*c2, c0, c1)."""
    return (f2_mul_xi(a[2]), a[0], a[1])


def f6_inv(a):
    a0, a1, a2 = a
    t0 = f2_sub(f2_sqr(a0), f2_mul_xi(f2_mul(a1, a2)))
    t1 = f2_sub(f2_mul_xi(f2_sqr(a2)), f2_mul(a0, a1))
    t2 = f2_sub(f2_sqr(a1), f2_mul(a0, a2))
    den = f2_add(
        f2_mul(a0, t0),
        f2_mul_xi(f2_add(f2_mul(a2, t1), f2_mul(a1, t2))),
    )
    inv = f2_inv(den)
    return (f2_mul(t0, inv), f2_mul(t1, inv), f2_mul(t2, inv))


F6_ZERO = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE = (F2_ONE, F2_ZERO, F2_ZERO)


# -- Fp12 = Fp6[w]/(w^2 - v): elements are (c0, c1) -------------------------


def f12_add(a, b):
    return (f6_add(a[0], b[0]), f6_add(a[1], b[1]))


def f12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    c0 = f6_add(t0, f6_mul_v(t1))
    c1 = f6_sub(f6_sub(f6_mul(f6_add(a0, a1), f6_add(b0, b1)), t0), t1)
    return (c0, c1)


def f12_sqr(a):
    return f12_mul(a, a)


def f12_conj(a):
    """Conjugation = Frobenius^6: (c0, -c1)."""
    return (a[0], f6_neg(a[1]))


def f12_inv(a):
    a0, a1 = a
    den = f6_inv(f6_sub(f6_sqr(a0), f6_mul_v(f6_sqr(a1))))
    return (f6_mul(a0, den), f6_neg(f6_mul(a1, den)))


def f12_pow(a, e):
    result = F12_ONE
    base = a
    while e:
        if e & 1:
            result = f12_mul(result, base)
        base = f12_sqr(base)
        e >>= 1
    return result


F12_ZERO = (F6_ZERO, F6_ZERO)
F12_ONE = (F6_ONE, F6_ZERO)


# -- Frobenius on Fp12 ------------------------------------------------------
# gamma_j = xi^(j*(p-1)/6), j = 1..5: the twist constants for conjugating each
# w^j coordinate. Computed once at import.

_GAMMA = [None] + [f2_pow(XI, j * (P - 1) // 6) for j in range(1, 6)]


def f12_frobenius(a):
    """x -> x^p. Coordinates as w-powers: (c00, c01 v, c02 v^2) + (c10 w, c11 vw, c12 v^2 w)
    = w-degrees (0, 2, 4) and (1, 3, 5)."""
    (c00, c01, c02), (c10, c11, c12) = a
    return (
        (
            f2_conj(c00),
            f2_mul(f2_conj(c01), _GAMMA[2]),
            f2_mul(f2_conj(c02), _GAMMA[4]),
        ),
        (
            f2_mul(f2_conj(c10), _GAMMA[1]),
            f2_mul(f2_conj(c11), _GAMMA[3]),
            f2_mul(f2_conj(c12), _GAMMA[5]),
        ),
    )


def f12_frobenius2(a):
    return f12_frobenius(f12_frobenius(a))


def f12_frobenius3(a):
    return f12_frobenius(f12_frobenius2(a))


# -- generic affine short-Weierstrass group ops -----------------------------
# Points are (x, y) tuples of field elements, or None for infinity. Generic
# over the field via a small ops record; used for G1 (Fp), G2' (Fp2) and the
# Fp12 lift inside the Miller loop.


class _FieldOps:
    def __init__(self, add, sub, mul, sqr, inv, neg, scalar, zero, one):
        self.add, self.sub, self.mul, self.sqr = add, sub, mul, sqr
        self.inv, self.neg, self.scalar = inv, neg, scalar
        self.zero, self.one = zero, one


def _fp_scalar(a, k):
    return a * k % P


FP_OPS = _FieldOps(
    lambda a, b: (a + b) % P,
    lambda a, b: (a - b) % P,
    lambda a, b: a * b % P,
    lambda a: a * a % P,
    lambda a: pow(a, -1, P),
    lambda a: (-a) % P,
    _fp_scalar,
    0,
    1,
)
F2_OPS = _FieldOps(
    f2_add, f2_sub, f2_mul, f2_sqr, f2_inv, f2_neg, f2_scalar, F2_ZERO, F2_ONE
)


def pt_is_on_curve(ops, pt, b):
    if pt is None:
        return True
    x, y = pt
    return ops.sub(ops.sqr(y), ops.add(ops.mul(ops.sqr(x), x), b)) == ops.zero


def pt_add(ops, p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 != y2:
            return None  # inverse points
        # doubling
        m = ops.mul(ops.scalar(ops.sqr(x1), 3), ops.inv(ops.scalar(y1, 2)))
    else:
        m = ops.mul(ops.sub(y2, y1), ops.inv(ops.sub(x2, x1)))
    x3 = ops.sub(ops.sub(ops.sqr(m), x1), x2)
    y3 = ops.sub(ops.mul(m, ops.sub(x1, x3)), y1)
    return (x3, y3)


def pt_neg(ops, pt):
    if pt is None:
        return None
    return (pt[0], ops.neg(pt[1]))


def pt_mul(ops, pt, k):
    """Scalar multiplication by the integer k as given — deliberately NOT
    reduced mod R: callers like the subgroup check below depend on [R]P
    actually performing the full ladder for points of unknown order."""
    result = None
    add = pt
    while k:
        if k & 1:
            result = pt_add(ops, result, add)
        add = pt_add(ops, add, add)
        k >>= 1
    return result


# -- G1 / G2 convenience ----------------------------------------------------

TWIST_B = f2_mul((3, 0), f2_inv(XI))  # 3 / xi, the E' curve coefficient


def g1_add(p1, p2):
    return pt_add(FP_OPS, p1, p2)


def g1_mul(pt, k):
    return pt_mul(FP_OPS, pt, k)


def g1_neg(pt):
    return pt_neg(FP_OPS, pt)


def g1_is_valid(pt):
    return pt_is_on_curve(FP_OPS, pt, B)


def g2_add(p1, p2):
    return pt_add(F2_OPS, p1, p2)


def g2_mul(pt, k):
    return pt_mul(F2_OPS, pt, k)


def g2_neg(pt):
    return pt_neg(F2_OPS, pt)


def g2_is_valid(pt):
    """On the twist AND in the order-r subgroup (E'(Fp2) has cofactor > 1)."""
    return pt_is_on_curve(F2_OPS, pt, TWIST_B) and (
        pt is None or g2_mul(pt, R) is None
    )


# -- pairing ----------------------------------------------------------------

# Fp12 "field ops" record for running generic point arithmetic on the lift
F12_OPS = _FieldOps(
    f12_add,
    lambda a, b: (f6_sub(a[0], b[0]), f6_sub(a[1], b[1])),
    f12_mul,
    f12_sqr,
    f12_inv,
    lambda a: (f6_neg(a[0]), f6_neg(a[1])),
    lambda a, k: f12_mul(a, _f12_from_int(k)),
    F12_ZERO,
    F12_ONE,
)


def _f12_from_int(k):
    return (((k % P, 0), F2_ZERO, F2_ZERO), F6_ZERO)


def _f12_from_f2_w2(a):
    """a * w^2 = a * v as an Fp12 element (w-degree 2 slot)."""
    return ((F2_ZERO, a, F2_ZERO), F6_ZERO)


def _f12_from_f2_w3(a):
    """a * w^3 = a * v * w (w-degree 3 slot)."""
    return (F6_ZERO, (F2_ZERO, a, F2_ZERO))


def twist(q):
    """Lift a point on E'(Fp2) to E(Fp12): psi(x', y') = (x' w^2, y' w^3)."""
    if q is None:
        return None
    return (_f12_from_f2_w2(q[0]), _f12_from_f2_w3(q[1]))


def _embed_g1(p):
    """Embed a G1 point into Fp12 coordinates."""
    return (_f12_from_int(p[0]), _f12_from_int(p[1]))


def _linefunc(p1, p2, t):
    """Evaluate the line through p1,p2 (or the tangent at p1 if equal) at t.

    Affine line function over Fp12 — the textbook formulation (cf. py_ecc);
    scaling factors are killed by the final exponentiation.
    """
    ops = F12_OPS
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = t
    if x1 != x2:
        m = ops.mul(ops.sub(y2, y1), ops.inv(ops.sub(x2, x1)))
        return ops.sub(ops.mul(m, ops.sub(xt, x1)), ops.sub(yt, y1))
    if y1 == y2:
        m = ops.mul(ops.scalar(ops.sqr(x1), 3), ops.inv(ops.scalar(y1, 2)))
        return ops.sub(ops.mul(m, ops.sub(xt, x1)), ops.sub(yt, y1))
    return ops.sub(xt, x1)


def miller_loop(q, p):
    """Miller loop of the optimal ate pairing: f_{6u+2,Q}(P) * line corrections.

    q: point on E'(Fp2) (G2), p: point on E(Fp) (G1). Returns an unreduced
    Fp12 value; apply final_exponentiation for the pairing.
    """
    if q is None or p is None:
        return F12_ONE
    ops = F12_OPS
    Q = twist(q)
    Pt = _embed_g1(p)
    Rpt = Q
    f = F12_ONE
    for bit in bin(ATE_LOOP_COUNT)[3:]:  # MSB-first, skipping the top bit
        f = ops.mul(ops.sqr(f), _linefunc(Rpt, Rpt, Pt))
        Rpt = pt_add(ops, Rpt, Rpt)
        if bit == "1":
            f = ops.mul(f, _linefunc(Rpt, Q, Pt))
            Rpt = pt_add(ops, Rpt, Q)
    # the two Frobenius correction lines of the optimal ate pairing
    Q1 = (f12_frobenius(Q[0]), f12_frobenius(Q[1]))
    nQ2 = (f12_frobenius2(Q[0]), F12_OPS.neg(f12_frobenius2(Q[1])))
    f = ops.mul(f, _linefunc(Rpt, Q1, Pt))
    Rpt = pt_add(ops, Rpt, Q1)
    f = ops.mul(f, _linefunc(Rpt, nQ2, Pt))
    return f


def miller_loop_projective(q, p):
    """Inversion-free Miller loop — the formulation the JAX/TPU kernel uses
    (ops/pairing.py), kept here in scalar form as its oracle.

    The accumulator point T runs in homogeneous projective coordinates on the
    twist E'(Fp2); lines are evaluated directly with Fp2 coefficients placed
    into the sparse Fp12 slots (1, w, w^3). All scale factors introduced live
    in Fp2 and die in the easy part of the final exponentiation.

    Derivation (D-twist psi(x,y) = (x w^2, y w^3), slope transforms as
    lambda' = lambda * w):
      doubling at T=(X,Y,Z), line scaled by 2YZ^3:
        l = 2YZ^2*yp - 3X^2 Z*xp w + (3X^3 - 2Y^2 Z) w^3
        n = 3X^2, d = 2YZ, e = n^2 - 8XY^2 Z:
        X' = e*d, Y' = n*(12XY^2 Z - n^2) - 8Y^4 Z^2, Z' = d^3
      mixed addition T + Q=(x2,y2), n = y2 Z - Y, d = x2 Z - X, line scaled
      by d:
        l = d*yp - n*xp w + (n x2 - d y2) w^3
        e = n^2 Z - (X + x2 Z) d^2:
        X' = e*d, Y' = n*(x2 Z d^2 - e) - y2 Z d^3, Z' = Z d^3
    """
    if q is None or p is None:
        return F12_ONE
    xp, yp = p

    def sparse_line(c0, cw, cw3):
        # Fp12 slots: 1 -> a0.u0, w -> a1.u0, w^3 = v*w -> a1.u1
        return ((c0, F2_ZERO, F2_ZERO), (cw, cw3, F2_ZERO))

    def dbl(T):
        X, Y, Z = T
        XX = f2_sqr(X)
        YY = f2_sqr(Y)
        YZ = f2_mul(Y, Z)
        n = f2_scalar(XX, 3)
        d = f2_scalar(YZ, 2)
        XYY = f2_mul(X, YY)
        XYYZ = f2_mul(XYY, Z)
        e = f2_sub(f2_sqr(n), f2_scalar(XYYZ, 8))
        X3 = f2_mul(e, d)
        Y3 = f2_sub(
            f2_mul(n, f2_sub(f2_scalar(XYYZ, 12), f2_sqr(n))),
            f2_scalar(f2_mul(f2_sqr(YY), f2_sqr(Z)), 8),
        )
        Z3 = f2_mul(f2_sqr(d), d)
        c0 = f2_scalar(f2_mul(f2_mul(YZ, Z), (yp, 0)), 2)
        cw = f2_neg(f2_mul(f2_mul(n, Z), (xp, 0)))
        cw3 = f2_sub(f2_mul(n, X), f2_scalar(f2_mul(YY, Z), 2))
        return (X3, Y3, Z3), sparse_line(c0, cw, cw3)

    def add(T, Q2):
        X, Y, Z = T
        x2, y2 = Q2
        n = f2_sub(f2_mul((y2[0], y2[1]), Z), Y)
        d = f2_sub(f2_mul((x2[0], x2[1]), Z), X)
        dd = f2_sqr(d)
        x2Z = f2_mul(x2, Z)
        e = f2_sub(f2_mul(f2_sqr(n), Z), f2_mul(f2_add(X, x2Z), dd))
        X3 = f2_mul(e, d)
        Y3 = f2_sub(
            f2_mul(n, f2_sub(f2_mul(x2Z, dd), e)),
            f2_mul(f2_mul(y2, Z), f2_mul(dd, d)),
        )
        Z3 = f2_mul(Z, f2_mul(dd, d))
        c0 = f2_mul(d, ((yp % P), 0))
        cw = f2_neg(f2_mul(n, ((xp % P), 0)))
        cw3 = f2_sub(f2_mul(n, x2), f2_mul(d, y2))
        return (X3, Y3, Z3), sparse_line(c0, cw, cw3)

    T = (q[0], q[1], F2_ONE)
    f = F12_ONE
    for bit in bin(ATE_LOOP_COUNT)[3:]:
        T, line = dbl(T)
        f = f12_mul(f12_sqr(f), line)
        if bit == "1":
            T, line = add(T, q)
            f = f12_mul(f, line)
    # Frobenius corrections on the untwisted coordinates:
    # psi-Frobenius on E': (x,y) -> (conj(x)*gamma_2', conj(y)*gamma_3') with
    # gamma coefficients matching the w^2/w^3 slots of the lift.
    q1 = (
        f2_mul(f2_conj(q[0]), _GAMMA[2]),
        f2_mul(f2_conj(q[1]), _GAMMA[3]),
    )
    q2 = (
        f2_mul(f2_conj(q1[0]), _GAMMA[2]),
        f2_neg(f2_mul(f2_conj(q1[1]), _GAMMA[3])),
    )
    T, line = add(T, q1)
    f = f12_mul(f, line)
    _, line = add(T, q2)
    f = f12_mul(f, line)
    return f


def final_exponentiation_naive(f):
    """The oracle: f^((p^12-1)/r) by plain square-and-multiply."""
    return f12_pow(f, (P**12 - 1) // R)


def final_exponentiation(f):
    """Fast final exponentiation: easy part by Frobenius/conjugation, hard part
    by the standard BN addition chain (Scott et al.), using that inversion is
    conjugation in the cyclotomic subgroup."""
    # easy part: f^((p^6-1)(p^2+1))
    f = f12_mul(f12_conj(f), f12_inv(f))  # f^(p^6-1)
    f = f12_mul(f12_frobenius2(f), f)  # ^(p^2+1)

    # hard part: f^((p^4 - p^2 + 1)/r)
    fu = f12_pow(f, U)
    fu2 = f12_pow(fu, U)
    fu3 = f12_pow(fu2, U)
    y0 = f12_mul(f12_mul(f12_frobenius(f), f12_frobenius2(f)), f12_frobenius3(f))
    y1 = f12_conj(f)
    y2 = f12_frobenius2(fu2)
    y3 = f12_conj(f12_frobenius(fu))
    y4 = f12_conj(f12_mul(fu, f12_frobenius(fu2)))
    y5 = f12_conj(fu2)
    y6 = f12_conj(f12_mul(fu3, f12_frobenius(fu3)))

    t0 = f12_mul(f12_mul(f12_sqr(y6), y4), y5)
    t1 = f12_mul(f12_mul(y3, y5), t0)
    t0 = f12_mul(t0, y2)
    t1 = f12_mul(f12_sqr(t1), t0)
    t1 = f12_sqr(t1)
    t0 = f12_mul(t1, y1)
    t1 = f12_mul(t1, y0)
    t0 = f12_sqr(t0)
    t0 = f12_mul(t0, t1)
    return t0


def pairing(q, p, fast: bool = True):
    """e(P in G1, Q in G2') -> GT (Fp12)."""
    f = miller_loop(q, p)
    return final_exponentiation(f) if fast else final_exponentiation_naive(f)


def pairing_check(pairs) -> bool:
    """Product-of-pairings check: prod e(p_i, q_i) == 1.

    One shared final exponentiation over the product of Miller loops — the
    batched structure the device kernel mirrors.
    """
    f = F12_ONE
    for p, q in pairs:
        f = f12_mul(f, miller_loop(q, p))
    return final_exponentiation(f) == F12_ONE

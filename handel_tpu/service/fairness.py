"""Deficit-round-robin tenant queue for the shared verify plane.

The single-tenant `BatchVerifierService` drained one FIFO list, which is
exactly wrong under multi-session load: one hot session (a flooded or very
large committee) enqueues faster than the collector drains and every other
session's candidates age behind its backlog. `TenantQueue` keeps one FIFO
per session and serves them deficit-round-robin [Shreedhar & Varghese '96,
degenerate unit-cost form — every verify candidate costs one launch lane]:
each tenant at the head of the active ring is charged `quantum` lane
credits per visit, spends them on its own candidates, and rotates to the
tail, so a full ring pass hands every backlogged session `quantum` lanes no
matter how deep any one backlog is. An emptied tenant forfeits its residual
deficit (no credit hoarding across idle periods — the standard DRR rule).

Per-tenant admission bound: `push` refuses beyond `max_pending` queued
items for one tenant, so a hot session's backlog is ITS problem — the
refusal surfaces to that session's caller (the processing pipeline's
retry/requeue budget) instead of growing host memory or the ring latency
every other tenant pays.

SLO-driven admission (lifecycle control plane, ISSUE 12c): each tenant
carries an `SloTier` — a priority weight that scales its DRR quantum (a
gold tenant earns `weight ×` lane credits per ring visit) plus a
load-shedding threshold expressed as a fraction of the queue's GLOBAL
`capacity`. When total depth crosses a tier's `shed_at` fraction, NEW work
for that tier is refused at the door — bronze sheds first, gold last — so
an overloaded plane spends its lanes meeting the strictest p99 targets
instead of degrading everyone equally. The flat per-tenant `max_pending`
bound stays as the fallback flood-defense knob; `capacity = 0` disables
shedding entirely (the pre-SLO behavior, byte for byte).

Single-threaded like the service it fronts (core/store.py module
docstring): every caller runs on one asyncio loop, so no lock.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Iterator

DEFAULT_QUANTUM = 8
DEFAULT_MAX_PENDING = 4096


@dataclass(frozen=True)
class SloTier:
    """One admission/priority class. `weight` multiplies the tenant's DRR
    quantum; `p99_target_s` is the session-completion SLO the manager
    reports against (service/session.py tier_quantiles); `shed_at` is the
    fraction of queue capacity past which this tier's new work sheds."""

    name: str
    weight: int = 1
    p99_target_s: float = 30.0
    shed_at: float = 1.0


#: the built-in tier ladder; tenants without an explicit tier ride
#: "standard" (weight 1, shed only at full capacity — legacy behavior)
TIERS = {
    "gold": SloTier("gold", weight=4, p99_target_s=5.0, shed_at=0.98),
    "silver": SloTier("silver", weight=2, p99_target_s=15.0, shed_at=0.85),
    "bronze": SloTier("bronze", weight=1, p99_target_s=60.0, shed_at=0.60),
    "standard": SloTier("standard", weight=1, p99_target_s=30.0, shed_at=1.0),
}
DEFAULT_TIER = TIERS["standard"]


class TenantQueue:
    """Per-tenant FIFOs drained fairly, `quantum` lanes per ring visit."""

    def __init__(
        self,
        quantum: int = DEFAULT_QUANTUM,
        max_pending: int = DEFAULT_MAX_PENDING,
        capacity: int = 0,
    ):
        if quantum < 1:
            raise ValueError("quantum must be >= 1")
        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        if capacity < 0:
            raise ValueError("capacity must be >= 0")
        self.quantum = quantum
        self.max_pending = max_pending
        # global depth bound for SLO shedding; 0 = shedding off
        self.capacity = capacity
        self._q: dict[str, deque] = {}
        self._ring: deque[str] = deque()  # tenants with queued work
        self._deficit: dict[str, int] = {}
        self._tier: dict[str, SloTier] = {}
        self._total = 0  # queued items across tenants (O(1) shed check)
        # reporter counters
        self.pushed = 0
        self.refused = 0
        self.shed = 0
        self.taken = 0

    def set_tier(self, tenant: str, tier: SloTier | str) -> SloTier:
        """Pin one tenant's admission/priority class ("gold"/"silver"/
        "bronze"/"standard", or a custom SloTier)."""
        if isinstance(tier, str):
            tier = TIERS[tier]
        self._tier[tenant] = tier
        return tier

    def tier_of(self, tenant: str) -> SloTier:
        return self._tier.get(tenant, DEFAULT_TIER)

    def drop_tier(self, tenant: str) -> None:
        self._tier.pop(tenant, None)

    def push(self, tenant: str, item) -> bool:
        """Enqueue one item for `tenant`; False = refused (the item was
        NOT queued — the caller owns the refusal). Two doors: the tier's
        load-shed threshold against GLOBAL depth, then the flat per-tenant
        bound."""
        if self.capacity > 0:
            tier = self.tier_of(tenant)
            if self._total >= self.capacity * tier.shed_at:
                self.shed += 1
                return False
        q = self._q.get(tenant)
        if q is None:
            q = self._q[tenant] = deque()
            self._ring.append(tenant)
            self._deficit[tenant] = 0
        if len(q) >= self.max_pending:
            self.refused += 1
            return False
        q.append(item)
        self._total += 1
        self.pushed += 1
        return True

    def shed_rate(self) -> float:
        """Shed pushes over everything offered (the soak SLO metric)."""
        offered = self.pushed + self.refused + self.shed
        return self.shed / offered if offered else 0.0

    def take(self, lanes: int) -> list:
        """Dequeue up to `lanes` items across tenants, deficit-round-robin.

        The head tenant keeps its position (and residual deficit) when the
        lane budget runs out mid-quantum, so fairness holds ACROSS calls:
        a launch boundary never resets whose turn it is.
        """
        out: list = []
        while lanes > 0 and self._ring:
            t = self._ring[0]
            q = self._q[t]
            d = self._deficit[t]
            if d <= 0:
                # tier weight scales the per-visit credit: a gold tenant
                # earns weight× lanes per ring pass (priority share)
                self._deficit[t] = d = (
                    self.quantum * self.tier_of(t).weight
                )
            k = min(d, len(q), lanes)
            for _ in range(k):
                out.append(q.popleft())
            self._total -= k
            self._deficit[t] = d - k
            lanes -= k
            if not q:
                # emptied: off the ring, residual deficit forfeited
                del self._q[t]
                self._ring.popleft()
                del self._deficit[t]
            elif self._deficit[t] == 0:
                self._ring.rotate(-1)  # quantum spent: next tenant's turn
            else:
                break  # lane budget exhausted mid-quantum: resume here
        self.taken += len(out)
        return out

    def drop_tenant(self, tenant: str) -> list:
        """Remove one tenant's whole queue (session evict); returns the
        dropped items so the caller can fail their waiters."""
        self.drop_tier(tenant)
        q = self._q.pop(tenant, None)
        if q is None:
            return []
        self._total -= len(q)
        self._deficit.pop(tenant, None)
        try:
            self._ring.remove(tenant)
        except ValueError:
            pass
        return list(q)

    def drain(self) -> Iterator:
        """Remove and yield every queued item (service stop())."""
        for t in list(self._q):
            yield from self.drop_tenant(t)

    def depth(self, tenant: str) -> int:
        q = self._q.get(tenant)
        return len(q) if q is not None else 0

    def depths(self) -> dict[str, int]:
        """Per-tenant queue depths (the `session`-labeled gauge surface)."""
        return {t: len(q) for t, q in self._q.items()}

    def tenants(self) -> int:
        return len(self._q)

    def __len__(self) -> int:
        return sum(len(q) for q in self._q.values())

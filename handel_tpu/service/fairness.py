"""Deficit-round-robin tenant queue for the shared verify plane.

The single-tenant `BatchVerifierService` drained one FIFO list, which is
exactly wrong under multi-session load: one hot session (a flooded or very
large committee) enqueues faster than the collector drains and every other
session's candidates age behind its backlog. `TenantQueue` keeps one FIFO
per session and serves them deficit-round-robin [Shreedhar & Varghese '96,
degenerate unit-cost form — every verify candidate costs one launch lane]:
each tenant at the head of the active ring is charged `quantum` lane
credits per visit, spends them on its own candidates, and rotates to the
tail, so a full ring pass hands every backlogged session `quantum` lanes no
matter how deep any one backlog is. An emptied tenant forfeits its residual
deficit (no credit hoarding across idle periods — the standard DRR rule).

Per-tenant admission bound: `push` refuses beyond `max_pending` queued
items for one tenant, so a hot session's backlog is ITS problem — the
refusal surfaces to that session's caller (the processing pipeline's
retry/requeue budget) instead of growing host memory or the ring latency
every other tenant pays.

Single-threaded like the service it fronts (core/store.py module
docstring): every caller runs on one asyncio loop, so no lock.
"""

from __future__ import annotations

from collections import deque
from typing import Iterator

DEFAULT_QUANTUM = 8
DEFAULT_MAX_PENDING = 4096


class TenantQueue:
    """Per-tenant FIFOs drained fairly, `quantum` lanes per ring visit."""

    def __init__(
        self,
        quantum: int = DEFAULT_QUANTUM,
        max_pending: int = DEFAULT_MAX_PENDING,
    ):
        if quantum < 1:
            raise ValueError("quantum must be >= 1")
        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        self.quantum = quantum
        self.max_pending = max_pending
        self._q: dict[str, deque] = {}
        self._ring: deque[str] = deque()  # tenants with queued work
        self._deficit: dict[str, int] = {}
        # reporter counters
        self.pushed = 0
        self.refused = 0
        self.taken = 0

    def push(self, tenant: str, item) -> bool:
        """Enqueue one item for `tenant`; False = over the per-tenant bound
        (the item was NOT queued — the caller owns the refusal)."""
        q = self._q.get(tenant)
        if q is None:
            q = self._q[tenant] = deque()
            self._ring.append(tenant)
            self._deficit[tenant] = 0
        if len(q) >= self.max_pending:
            self.refused += 1
            return False
        q.append(item)
        self.pushed += 1
        return True

    def take(self, lanes: int) -> list:
        """Dequeue up to `lanes` items across tenants, deficit-round-robin.

        The head tenant keeps its position (and residual deficit) when the
        lane budget runs out mid-quantum, so fairness holds ACROSS calls:
        a launch boundary never resets whose turn it is.
        """
        out: list = []
        while lanes > 0 and self._ring:
            t = self._ring[0]
            q = self._q[t]
            d = self._deficit[t]
            if d <= 0:
                self._deficit[t] = d = self.quantum
            k = min(d, len(q), lanes)
            for _ in range(k):
                out.append(q.popleft())
            self._deficit[t] = d - k
            lanes -= k
            if not q:
                # emptied: off the ring, residual deficit forfeited
                del self._q[t]
                self._ring.popleft()
                del self._deficit[t]
            elif self._deficit[t] == 0:
                self._ring.rotate(-1)  # quantum spent: next tenant's turn
            else:
                break  # lane budget exhausted mid-quantum: resume here
        self.taken += len(out)
        return out

    def drop_tenant(self, tenant: str) -> list:
        """Remove one tenant's whole queue (session evict); returns the
        dropped items so the caller can fail their waiters."""
        q = self._q.pop(tenant, None)
        if q is None:
            return []
        self._deficit.pop(tenant, None)
        try:
            self._ring.remove(tenant)
        except ValueError:
            pass
        return list(q)

    def drain(self) -> Iterator:
        """Remove and yield every queued item (service stop())."""
        for t in list(self._q):
            yield from self.drop_tenant(t)

    def depth(self, tenant: str) -> int:
        q = self._q.get(tenant)
        return len(q) if q is not None else 0

    def depths(self) -> dict[str, int]:
        """Per-tenant queue depths (the `session`-labeled gauge surface)."""
        return {t: len(q) for t, q in self._q.items()}

    def tenants(self) -> int:
        return len(self._q)

    def __len__(self) -> int:
        return sum(len(q) for q in self._q.values())

"""Aggregation-session lifecycle: spawn → running → threshold → expire/evict.

One `Session` is one aggregation instance — a distinct message over its own
committee of logical Handel nodes (an in-process cluster on the shared
event loop, core/test_harness.py). The `SessionManager` multiplexes many of
them onto ONE shared verify plane: every node's Config.verifier is the
shared `BatchVerifierService`'s session-tagged wrapper, so all sessions'
candidates coalesce into the same device launches under the tenant queue's
deficit-round-robin fairness (service/fairness.py), while the per-tenant
state — dedup verdicts, peer penalties, queue bounds — stays keyed by the
session id and is dropped wholesale when the session retires.

Lifecycle:

    spawn   admission-controlled (bounded live-session cap; a finished
            session still held is evicted to make room, else the spawn is
            refused) — nodes are built but not started
    running start() — nodes aggregate; a watcher task awaits completion
    threshold-reached
            every online node emitted a final signature >= threshold; the
            session's nodes stop, its shared-plane state is released, its
            completion latency feeds the manager's p50/p99 surface
    expired the watcher hit the session TTL first — same teardown
    evicted external removal (cap pressure, operator) at any state
"""

from __future__ import annotations

import asyncio
import random
import time
from collections import deque
from typing import Callable, Sequence

from handel_tpu.core.config import Config
from handel_tpu.core.penalty import SessionScorers
from handel_tpu.core.test_harness import FakeScheme, LocalCluster

STATE_SPAWNED = "spawned"
STATE_RUNNING = "running"
STATE_DONE = "threshold-reached"
STATE_EXPIRED = "expired"
STATE_EVICTED = "evicted"

#: numeric form for the metrics plane (handel_service_state{session=...})
STATE_CODE = {
    STATE_SPAWNED: 0.0,
    STATE_RUNNING: 1.0,
    STATE_DONE: 2.0,
    STATE_EXPIRED: 3.0,
    STATE_EVICTED: 4.0,
}


class AdmissionRefused(RuntimeError):
    """spawn() refused: the live-session cap is full of running sessions."""


class Session:
    """One aggregation instance over its own committee (see module doc)."""

    def __init__(
        self,
        sid: str,
        n: int,
        *,
        threshold: int | None = None,
        msg: bytes | None = None,
        scheme=None,
        service=None,
        scorers: SessionScorers | None = None,
        offline: Sequence[int] = (),
        seed: int = 0,
        ttl_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
        config_tweak: Callable[[Config, int], None] | None = None,
        recorder=None,
        epoch: int = 0,
    ):
        self.sid = sid
        self.n = n
        self.clock = clock
        self.ttl_s = ttl_s
        # validator-set epoch this session was spawned under (lifecycle/
        # epoch.py): rides every node Config into dedup keys + trace spans
        self.epoch = epoch
        self.state = STATE_SPAWNED
        self.created_at = clock()
        self.started_at: float | None = None
        self.completed_at: float | None = None
        self.msg = msg if msg is not None else f"session:{sid}".encode()
        self.service = service
        self.finals = None
        self._done_cb: Callable[["Session"], None] | None = None
        self._watch_task: asyncio.Task | None = None

        verifier = (
            service.session_verifier(sid) if service is not None else None
        )

        def factory(i: int) -> Config:
            cfg = Config()
            # per-tenant keying end to end: the session id scopes this
            # node's dedup keys (core/processing.py) and, via the tagged
            # verifier, its share of the fairness queue and the service
            # dedup plane
            cfg.session = sid
            cfg.epoch = epoch
            # shared flight recorder (core/trace.py): every node of every
            # session records into one ring, spans tagged by session above
            cfg.recorder = recorder
            cfg.rand = random.Random(seed * 100003 + i)
            if verifier is not None:
                cfg.verifier = verifier
            if scorers is not None:
                # penalties keyed by session: this committee's trust
                # domain, dropped wholesale at retirement
                cfg.new_scorer = lambda h, _s=scorers: _s.for_session(sid)
            if config_tweak is not None:
                config_tweak(cfg, i)
            return cfg

        self.cluster = LocalCluster(
            n,
            scheme=scheme,
            threshold=threshold,
            offline=offline,
            msg=self.msg,
            config_factory=factory,
            seed=seed,
        )
        self.threshold = self.cluster.threshold

    # -- lifecycle ---------------------------------------------------------

    def start(self, on_done: Callable[["Session"], None] | None = None) -> None:
        """spawned -> running; the watcher resolves the terminal state.
        Must be called from a running asyncio loop."""
        if self.state != STATE_SPAWNED:
            raise RuntimeError(f"session {self.sid} already {self.state}")
        self.state = STATE_RUNNING
        self.started_at = self.clock()
        self._done_cb = on_done
        self.cluster.start()
        self._watch_task = asyncio.get_running_loop().create_task(
            self._watch()
        )

    async def _watch(self) -> None:
        try:
            self.finals = await self.cluster.wait_complete_success(self.ttl_s)
        except asyncio.TimeoutError:
            self._finish(STATE_EXPIRED)
            return
        except asyncio.CancelledError:
            raise
        self._finish(STATE_DONE)

    def _finish(self, state: str) -> None:
        if self.state != STATE_RUNNING:
            return
        self.completed_at = self.clock()
        self.state = state
        self.cluster.stop()
        if self._done_cb is not None:
            self._done_cb(self)

    def stop(self) -> None:
        """Tear the session down without a state transition of its own
        (evict() owns the bookkeeping)."""
        if self._watch_task is not None:
            self._watch_task.cancel()
            self._watch_task = None
        self.cluster.stop()

    # -- introspection ------------------------------------------------------

    @property
    def finished(self) -> bool:
        return self.state in (STATE_DONE, STATE_EXPIRED, STATE_EVICTED)

    def completion_s(self) -> float | None:
        """Wall seconds from start to the terminal transition."""
        if self.completed_at is None or self.started_at is None:
            return None
        return self.completed_at - self.started_at

    def pending_work(self) -> int:
        """Unverified candidates attributable to this session: the nodes'
        own processing queues plus its share of the shared verifier queue."""
        pending = sum(
            len(h.proc.pending()) for h in self.cluster.handels.values()
        )
        if self.service is not None:
            pending += self.service.queue.depth(self.sid)
        return pending

    def nodes_done(self) -> int:
        return sum(
            1
            for h in self.cluster.handels.values()
            if h.best is not None
        )

    def best_cardinality(self) -> int:
        return max(
            (
                h.best.cardinality()
                for h in self.cluster.handels.values()
                if h.best is not None
            ),
            default=0,
        )

    def values(self) -> dict[str, float]:
        """Per-session sample set for the `session`-labeled metrics plane."""
        return {
            "state": STATE_CODE[self.state],
            "pending": float(self.pending_work()),
            "nodesDone": float(self.nodes_done()),
            "nodes": float(self.n),
            "bestCardinality": float(self.best_cardinality()),
            "threshold": float(self.threshold),
            "ageS": self.clock() - self.created_at,
            "completionS": self.completion_s() or 0.0,
        }


def _quantile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


class SessionManager:
    """Admission-controlled registry of concurrent aggregation sessions.

    `max_sessions` bounds the HELD set — every session whose state (nodes,
    results, per-tenant planes) this process still carries, live or
    finished: a spawn at the cap first evicts a finished session still
    held (freeing its retained results and shared-plane state), and
    refuses with `AdmissionRefused` when every held session is genuinely
    live — backpressure the caller (an ingress layer, the sim driver)
    must surface, not absorb.
    """

    def __init__(
        self,
        service=None,
        scheme=None,
        max_sessions: int = 64,
        session_ttl_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
        scorers: SessionScorers | None = None,
        retired_capacity: int = 4096,
        recorder=None,
    ):
        if max_sessions < 1:
            raise ValueError("max_sessions must be >= 1")
        self.service = service
        self.recorder = recorder
        self.scheme = scheme or FakeScheme()
        self.max_sessions = max_sessions
        self.session_ttl_s = session_ttl_s
        self.clock = clock
        self.scorers = scorers or SessionScorers()
        self.sessions: dict[str, Session] = {}
        # terminal records of evicted sessions: (sid, state, completion_s)
        self.retired: deque = deque(maxlen=retired_capacity)
        self.completion_s: list[float] = []  # every threshold-reached run
        # lifecycle plane: the epoch new sessions spawn under (bumped by
        # lifecycle/epoch.py EpochManager.commit) + per-tenant SLO tiers
        # and their completion-latency buckets (service/fairness.py TIERS)
        self.epoch = 0
        self.tiers: dict[str, str] = {}
        self.completion_by_tier: dict[str, list[float]] = {}
        self._seq = 0
        # reporter counters
        self.spawned_ct = 0
        self.completed_ct = 0
        self.expired_ct = 0
        self.evicted_ct = 0
        self.refused_ct = 0

    # -- admission + lifecycle ----------------------------------------------

    def live_count(self) -> int:
        return sum(
            1
            for s in self.sessions.values()
            if s.state in (STATE_SPAWNED, STATE_RUNNING)
        )

    def spawn(
        self,
        n: int,
        *,
        sid: str | None = None,
        threshold: int | None = None,
        msg: bytes | None = None,
        offline: Sequence[int] = (),
        seed: int | None = None,
        ttl_s: float | None = None,
        config_tweak=None,
        tier: str | None = None,
    ) -> Session:
        if len(self.sessions) >= self.max_sessions:
            # cap pressure: finished sessions still held are reclaimable
            if not self._evict_one_finished() or (
                len(self.sessions) >= self.max_sessions
            ):
                self.refused_ct += 1
                raise AdmissionRefused(
                    f"{self.live_count()} live / {len(self.sessions)} held "
                    f"sessions at cap {self.max_sessions}"
                )
        self._seq += 1
        sid = sid if sid is not None else f"s{self._seq}"
        if sid in self.sessions:
            raise ValueError(f"session id {sid!r} already exists")
        s = Session(
            sid,
            n,
            threshold=threshold,
            msg=msg,
            scheme=self.scheme,
            service=self.service,
            scorers=self.scorers,
            offline=offline,
            seed=self._seq if seed is None else seed,
            ttl_s=self.session_ttl_s if ttl_s is None else ttl_s,
            clock=self.clock,
            config_tweak=config_tweak,
            recorder=self.recorder,
            epoch=self.epoch,
        )
        if tier is not None:
            # SLO class end to end: recorded here for the per-tier p99
            # surface, pinned on the shared verifier's tenant queue for
            # weighted DRR + load shedding (service/fairness.py)
            self.tiers[sid] = tier
            if self.service is not None:
                self.service.queue.set_tier(sid, tier)
        self.sessions[sid] = s
        self.spawned_ct += 1
        return s

    def start(self, sid: str, on_done=None) -> None:
        """Start a spawned session; `on_done` (optional) observes the
        terminal session AFTER the manager's own accounting — the hook an
        ingress layer (service/federation.py front door) tracks per-arrival
        outcomes with."""
        if on_done is None:
            self.sessions[sid].start(on_done=self._on_session_end)
            return

        def chained(s: Session) -> None:
            self._on_session_end(s)
            on_done(s)

        self.sessions[sid].start(on_done=chained)

    def _on_session_end(self, s: Session) -> None:
        """Watcher callback at threshold-reached/expired: account the
        outcome and release the tenant's shared-plane state (its nodes are
        already stopped — nothing will enqueue under this id again)."""
        if s.state == STATE_DONE:
            self.completed_ct += 1
            done_in = s.completion_s()
            if done_in is not None:
                self.completion_s.append(done_in)
                tier = self.tiers.get(s.sid)
                if tier is not None:
                    self.completion_by_tier.setdefault(tier, []).append(
                        done_in
                    )
        else:
            self.expired_ct += 1
        self._forget_tenant(s.sid)

    def _forget_tenant(self, sid: str) -> None:
        if self.service is not None:
            self.service.forget_session(sid)
        self.scorers.drop(sid)
        # tier mapping is per-live-session state (the per-tier completion
        # buckets above already banked this session's latency)
        self.tiers.pop(sid, None)

    def evict(self, sid: str) -> bool:
        """Remove a session at any state; a live one transitions to
        `evicted` (its nodes stop mid-flight)."""
        s = self.sessions.pop(sid, None)
        if s is None:
            return False
        was_live = s.state in (STATE_SPAWNED, STATE_RUNNING)
        s.stop()
        if was_live:
            s.state = STATE_EVICTED
            s.completed_at = self.clock()
            self.evicted_ct += 1
        self._forget_tenant(sid)
        self.retired.append((sid, s.state, s.completion_s()))
        return True

    def _evict_one_finished(self) -> bool:
        for sid, s in self.sessions.items():
            if s.finished:
                return self.evict(sid)
        return False

    async def wait_all(self, timeout: float) -> None:
        """Await every currently-running session's watcher (terminal state
        reached: done or expired)."""
        tasks = [
            s._watch_task
            for s in list(self.sessions.values())
            if s._watch_task is not None
        ]
        if tasks:
            await asyncio.wait_for(
                asyncio.gather(*tasks, return_exceptions=True), timeout
            )

    def stop(self) -> None:
        for sid in list(self.sessions):
            self.evict(sid)

    # -- reporting -----------------------------------------------------------

    def tier_quantiles(self) -> dict[str, dict[str, float]]:
        """Per-SLO-tier completion latency against its target
        (service/fairness.py TIERS): the soak harness's "p99 held within
        its tier" acceptance surface."""
        from handel_tpu.service.fairness import DEFAULT_TIER, TIERS

        out: dict[str, dict[str, float]] = {}
        for tier, vals in self.completion_by_tier.items():
            done = sorted(vals)
            target = TIERS.get(tier, DEFAULT_TIER).p99_target_s
            p99 = _quantile(done, 0.99)
            out[tier] = {
                "completed": float(len(done)),
                "p50_s": _quantile(done, 0.50),
                "p99_s": p99,
                "target_s": target,
                "met": 1.0 if p99 <= target else 0.0,
            }
        return out

    def values(self) -> dict[str, float]:
        done = sorted(self.completion_s)
        return {
            "sessionsLive": float(self.live_count()),
            "sessionsHeld": float(len(self.sessions)),
            "sessionsSpawned": float(self.spawned_ct),
            "sessionsCompleted": float(self.completed_ct),
            "sessionsExpired": float(self.expired_ct),
            "sessionsEvicted": float(self.evicted_ct),
            "admissionRefused": float(self.refused_ct),
            "sessionCompletionP50S": _quantile(done, 0.50),
            "sessionCompletionP99S": _quantile(done, 0.99),
            "epoch": float(self.epoch),
        }

    def gauge_keys(self) -> set[str]:
        return {
            "sessionsLive",
            "sessionsHeld",
            "sessionCompletionP50S",
            "sessionCompletionP99S",
            "epoch",
        }

    def labeled_values(self) -> dict[str, dict[str, float]]:
        """{session id: per-session values} for the session-labeled plane
        (core/metrics.py register_labeled_values; `sim watch` renders the
        top-K rows by pending work). Includes the shared verifier's
        per-tenant counters when a service is wired."""
        out = {sid: s.values() for sid, s in self.sessions.items()}
        if self.service is not None:
            for sid, vals in self.service.session_values().items():
                out.setdefault(sid, {}).update(vals)
        return out

    def labeled_gauge_keys(self) -> set[str]:
        keys = {
            "state", "pending", "nodesDone", "nodes", "bestCardinality",
            "threshold", "ageS", "completionS",
        }
        if self.service is not None:
            keys |= self.service.session_gauge_keys()
        return keys

"""Multi-tenant aggregation service: many committees, one device plane.

ROADMAP item 3: "millions of users" means many concurrent aggregation
instances — distinct messages, rounds, committees — not one big one. This
package multiplexes N concurrent Handel sessions onto ONE
`BatchVerifierService` (parallel/batch_verifier.py) and one warm device
plane: a `SessionManager` owns session lifecycle (spawn → running →
threshold-reached → expire/evict) behind a bounded concurrent-session cap,
the verifier's tenant-tagged queue coalesces every session's pending
candidates into shared 64/128-lane launches under a deficit-round-robin
fairness policy (`TenantQueue`), and the per-tenant state — dedup verdicts,
peer penalties, queue bounds — is keyed by session id so evicting a tenant
drops its footprint wholesale.

Grounded in the ACE runtime direction (PAPERS.md, arxiv 2603.10242):
sub-second cryptographic finality as a continuously-loaded multiplexed
service rather than a one-shot run.
"""

from handel_tpu.service.fairness import TenantQueue
from handel_tpu.service.federation import (
    Federation,
    FrontDoor,
    RegionDead,
    RegionPlane,
    RegionShedding,
)
from handel_tpu.service.session import (
    AdmissionRefused,
    Session,
    SessionManager,
    STATE_DONE,
    STATE_EVICTED,
    STATE_EXPIRED,
    STATE_RUNNING,
    STATE_SPAWNED,
)

__all__ = [
    "AdmissionRefused",
    "Federation",
    "FrontDoor",
    "RegionDead",
    "RegionPlane",
    "RegionShedding",
    "Session",
    "SessionManager",
    "TenantQueue",
    "STATE_SPAWNED",
    "STATE_RUNNING",
    "STATE_DONE",
    "STATE_EXPIRED",
    "STATE_EVICTED",
]

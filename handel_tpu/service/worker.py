"""Service worker process: one shard of a `sim serve` session load.

Spawned by service/driver.py `run_service` when `[service].processes > 1`:
each worker multiplexes its share of the sessions onto its OWN shared
`BatchVerifierService` (one verify plane per process — the fleet analog of
the per-process shared verifier in sim/node.py), optionally serves
/metrics with the session-labeled plane, and reports its summary on stdout
as one `SERVICE_RESULT {json}` line for the driver to merge.

Run as: python -m handel_tpu.service.worker --config serve.toml
            --index I --sessions K [--metrics-port P]
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import json
import sys


async def run_worker(args) -> int:
    from handel_tpu.sim.config import load_config
    from handel_tpu.service.driver import run_in_process

    cfg = load_config(args.config)
    # this worker runs `--sessions` of the total; seeds are disjoint per
    # worker so no two workers build identical committees
    cfg.service = dataclasses.replace(cfg.service, sessions=args.sessions)
    summary = await run_in_process(
        cfg,
        seed_base=args.index * 1_000_000,
        metrics_port=args.metrics_port if args.metrics_port >= 0 else None,
    )
    summary["worker"] = args.index
    print("SERVICE_RESULT " + json.dumps(summary), flush=True)
    return 0 if summary["expired"] == 0 else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", required=True)
    ap.add_argument("--index", type=int, default=0)
    ap.add_argument("--sessions", type=int, required=True)
    ap.add_argument("--metrics-port", type=int, default=-1)
    return asyncio.run(run_worker(ap.parse_args()))


if __name__ == "__main__":
    sys.exit(main())

"""Geo-federated verify planes behind an RTT-routing front door.

One `MultiSessionCluster` (service/driver.py) per region of a planet
preset (scenario/planets.py) makes a *federation*: the service no longer
lives or dies with one cluster. Arrivals enter through a `FrontDoor`
that routes each session to the nearest healthy region by the planet's
RTT matrix (`GeoConfig.rtt`), with three defenses layered in order:

- **spill-over** — when the nearest region refuses (its SLO shed bound,
  fairness.py `shed_at` against the global queue depth; its live-session
  cap; or it is dead), the arrival immediately tries the next region by
  RTT. A spilled session pays the extra WAN leg but completes.
- **health probes** — the front door routes on its own learned health
  map, refreshed every `probe_interval_s`; a routing attempt that finds
  a region dead marks it down passively (no full probe interval of
  misroutes after a kill).
- **capped-exponential-backoff retry** — when EVERY region refuses, the
  arrival waits `min(retry_cap_ms, retry_base_ms * 2^attempt)` and
  re-routes, up to `retry_budget` attempts; only then does it fail, and
  the failure is attributed (shed vs dead) — never a silent drop.

Chaos rides at this level too: `Federation.kill_region` stops a region's
cluster mid-flight (its live sessions are handed back for re-routing),
and `Federation.recover_region` rebuilds it and rejoins it via the
existing epoch path — the fresh cluster stages the current validator
set, quiesces, and flips (lifecycle/epoch.py over `quiesce_and`), so
re-admission is a registry rotation, not a cold restart. Every
transition is traced with region-tagged spans (`args={"region": ...}`),
which is what lets `sim trace --critical-path` attribute which leg a
late session waited on.

Driven open-loop by sim/load.py (`python -m handel_tpu.sim load`);
configured by the `[federation]` TOML section (sim/config.py).
"""

from __future__ import annotations

import asyncio
import time

from handel_tpu.core.logging import DEFAULT_LOGGER, Logger
from handel_tpu.core.test_harness import FakeScheme
from handel_tpu.core.trace import SERVICE_TID, trace_now
from handel_tpu.network.geo import GeoConfig
from handel_tpu.scenario.planets import planet_preset
from handel_tpu.service.fairness import DEFAULT_TIER, TIERS
from handel_tpu.service.session import AdmissionRefused, Session


class RegionShedding(RuntimeError):
    """Region refused an arrival at its SLO shed bound: spill it."""


class RegionDead(RuntimeError):
    """Region's cluster is stopped (killed, not yet recovered)."""


class RegionPlane:
    """One geo region's service plane: a MultiSessionCluster plus the
    admission/health surface the front door routes against.

    The cluster is rebuilt wholesale on recovery, so the counters a
    report needs cumulatively (completions, sheds, queue offers) are
    banked here across rebuilds — `stats()` is always lifetime totals.
    """

    def __init__(self, name: str, index: int, p, *, scheme=None,
                 recorder=None, logger: Logger = DEFAULT_LOGGER):
        self.name = name
        self.index = index
        self.p = p
        self.scheme = scheme or FakeScheme()
        self.recorder = recorder
        self.log = logger
        self.killed = False
        # front-door attribution counters (lifetime, never rebuilt)
        self.arrivals = 0  # arrivals whose nearest region is this one
        self.admitted = 0
        self.spill_in = 0  # admitted here after a nearer region refused
        self.sheds = 0  # session-level refusals at the shed bound
        self.refusals = 0  # refusals at the live-session cap
        self.kills = 0
        self.recoveries = 0
        self._banked = {
            "completed": 0, "expired": 0, "evicted": 0, "spawned": 0,
            "pushed": 0, "refused": 0, "shed": 0,
        }
        self.cluster: MultiSessionCluster | None = None
        self._build()

    def _build(self) -> None:
        # deferred: driver -> parallel -> mesh_plane -> service would
        # otherwise close an import cycle through this module
        from handel_tpu.service.driver import MultiSessionCluster

        p = self.p
        self.cluster = MultiSessionCluster(
            sessions=0,  # open-loop arrivals drive it, not cluster.run()
            nodes=0,
            scheme=self.scheme,
            devices=p.devices,
            batch_size=p.batch_size,
            max_sessions=p.max_sessions,
            session_ttl_s=p.session_ttl_s,
            queue_capacity=p.queue_capacity,
            recorder=self.recorder,
        )

    def start(self) -> None:
        self.cluster.service.start()

    @property
    def healthy(self) -> bool:
        """Ground truth (what a probe reaching the region would see) —
        the front door routes on its own learned view, not this."""
        return not self.killed

    def live_count(self) -> int:
        return self.cluster.manager.live_count()

    def shedding(self, tier: str | None) -> bool:
        """Session-level mirror of the queue's candidate-level shed door
        (fairness.py push): admitting a session whose tier would shed
        every candidate it enqueues only wastes its committee's work."""
        q = self.cluster.service.queue
        if q.capacity <= 0:
            return False
        t = TIERS.get(tier or "", DEFAULT_TIER)
        return len(q) >= q.capacity * t.shed_at

    def admit(self, *, nodes: int, tier: str | None, seed: int,
              on_done=None) -> Session:
        """One arrival: spawn + start a session here, or refuse with
        attribution (RegionDead / RegionShedding / AdmissionRefused)."""
        if self.killed:
            raise RegionDead(self.name)
        if self.shedding(tier):
            self.sheds += 1
            raise RegionShedding(f"{self.name} at shed bound")

        def tweak(node_cfg, i):
            node_cfg.update_period = self.p.period_ms / 1000.0
            # region-tagged spans end to end (core/handel.py _sargs):
            # the critical-path walk attributes hops to region pairs
            node_cfg.region = self.name

        m = self.cluster.manager
        try:
            s = m.spawn(nodes, seed=seed, tier=tier, config_tweak=tweak)
        except AdmissionRefused:
            self.refusals += 1
            raise
        self.admitted += 1
        m.start(s.sid, on_done=on_done)
        return s

    def kill(self) -> list[str]:
        """Chaos: stop this region's whole cluster mid-flight. Returns the
        sids that were live — the caller (sim/load.py) re-routes those
        arrivals through the front door, so a region loss is latency, not
        loss."""
        live = [
            sid for sid, s in self.cluster.manager.sessions.items()
            if not s.finished
        ]
        self.killed = True
        self.kills += 1
        self._bank()
        self.cluster.stop()
        if self.recorder is not None:
            self.recorder.instant(
                "region_kill", tid=SERVICE_TID, cat="federation",
                args={"region": self.name},
            )
        return live

    def revive(self) -> None:
        """Rebuild a fresh cluster for this region. The caller owns the
        rejoin choreography (epoch staging + front-door re-admission) —
        this only restores the machinery."""
        self._build()
        self.cluster.service.start()
        self.killed = False
        self.recoveries += 1
        if self.recorder is not None:
            self.recorder.instant(
                "region_recover", tid=SERVICE_TID, cat="federation",
                args={"region": self.name},
            )

    def _bank(self) -> None:
        """Fold the dying cluster's counters into the lifetime totals
        before the rebuild discards them."""
        m = self.cluster.manager
        q = self.cluster.service.queue
        b = self._banked
        b["completed"] += m.completed_ct
        b["expired"] += m.expired_ct
        b["evicted"] += m.evicted_ct
        b["spawned"] += m.spawned_ct
        b["pushed"] += q.pushed
        b["refused"] += q.refused
        b["shed"] += q.shed

    def stats(self) -> dict[str, float]:
        """Lifetime per-region sample set (the `region`-labeled metrics
        plane: handel_federation_*{region="..."})."""
        m = self.cluster.manager
        q = self.cluster.service.queue
        b = self._banked
        shed = b["shed"] + q.shed
        offered = shed + b["pushed"] + q.pushed + b["refused"] + q.refused
        return {
            "regionHealthy": 0.0 if self.killed else 1.0,
            "arrivals": float(self.arrivals),
            "admitted": float(self.admitted),
            "spillIn": float(self.spill_in),
            "shed": float(self.sheds),
            "refused": float(self.refusals),
            "sessionsLive": float(0 if self.killed else m.live_count()),
            "completed": float(b["completed"] + m.completed_ct),
            "expired": float(b["expired"] + m.expired_ct),
            "evicted": float(b["evicted"] + m.evicted_ct),
            # candidate-level shed rate of this region's verify plane
            "shedRate": shed / offered if offered else 0.0,
            "epoch": float(m.epoch),
            "kills": float(self.kills),
        }


class FrontDoor:
    """Routes each arriving session to the nearest healthy region by RTT.

    Routing is deterministic: per-origin region orders are precomputed
    from the RTT matrix with a name tie-break, and health transitions are
    the only routing state — same seed, same planet, same kills means
    the same region choice for every arrival.
    """

    def __init__(self, geo: GeoConfig, planes: list[RegionPlane], p, *,
                 recorder=None, logger: Logger = DEFAULT_LOGGER):
        self.geo = geo
        self.planes = {r.name: r for r in planes}
        self.p = p
        self.recorder = recorder
        self.log = logger
        self.health: dict[str, bool] = {r.name: True for r in planes}
        self.unhealthy_at: dict[str, float] = {}  # detection timestamps
        self.rehealthy_at: dict[str, float] = {}
        self.retries = 0
        self.spillovers = 0
        self.sheds = 0  # arrivals that exhausted the budget on shed doors
        self.failures = 0  # arrivals that exhausted it on dead regions
        self.probe_rounds = 0
        self.markdowns = 0  # monotonic healthy->down transitions
        self._probe_task: asyncio.Task | None = None
        # nearest-first routing tables, one per origin region
        self._order = {
            o: sorted(self.planes, key=lambda r: (geo.rtt(o, r), r))
            for o in self.planes
        }

    # -- health -------------------------------------------------------------

    def backoff_ms(self, attempt: int) -> float:
        """Capped exponential retry delay for 0-based `attempt`."""
        return min(
            self.p.retry_cap_ms, self.p.retry_base_ms * (2.0 ** attempt)
        )

    def mark(self, name: str, healthy: bool) -> None:
        if self.health[name] == healthy:
            return
        self.health[name] = healthy
        if not healthy:
            self.markdowns += 1
        (self.rehealthy_at if healthy else self.unhealthy_at)[name] = (
            time.monotonic()
        )
        if self.recorder is not None:
            self.recorder.instant(
                "frontdoor_mark_" + ("up" if healthy else "down"),
                tid=SERVICE_TID, cat="federation", args={"region": name},
            )
        self.log.info(
            "federation",
            f"front door marks {name} {'healthy' if healthy else 'DOWN'}",
        )

    def probe_now(self) -> None:
        """One health-probe round (the background loop's body; tests call
        it directly for deterministic transitions)."""
        self.probe_rounds += 1
        for name, plane in self.planes.items():
            self.mark(name, plane.healthy)

    async def _probe_loop(self) -> None:
        while True:
            await asyncio.sleep(self.p.probe_interval_s)
            self.probe_now()

    def start(self) -> None:
        self._probe_task = asyncio.get_running_loop().create_task(
            self._probe_loop()
        )

    async def stop(self) -> None:
        if self._probe_task is None:
            return
        self._probe_task.cancel()
        try:
            await self._probe_task
        except asyncio.CancelledError:
            pass
        self._probe_task = None

    # -- routing ------------------------------------------------------------

    def route_order(self, origin: str) -> list[str]:
        """Healthy regions nearest-first by RTT from `origin`."""
        return [r for r in self._order[origin] if self.health[r]]

    async def submit(self, origin: str, *, nodes: int, tier: str | None,
                     seed: int, on_done=None):
        """Route one arrival. Returns (outcome, session, region, attempts)
        with outcome "admitted" | "shed" | "failed" — an arrival NEVER
        vanishes: it lands, sheds with attribution, or fails its traced
        retry budget."""
        p = self.p
        primary = self._order[origin][0]
        self.planes[primary].arrivals += 1
        t0 = trace_now()
        attempts = 0
        shed_seen = False
        while True:
            for name in self.route_order(origin):
                plane = self.planes[name]
                # the WAN leg: the front door sits with the arrival's
                # origin, so reaching a farther region costs its RTT/2
                rtt = self.geo.rtt(origin, name)
                if rtt > 0:
                    await asyncio.sleep(rtt / 2.0 / 1000.0)
                try:
                    s = plane.admit(
                        nodes=nodes, tier=tier, seed=seed, on_done=on_done
                    )
                except RegionDead:
                    self.mark(name, False)  # passive detection
                    continue
                except RegionShedding:
                    shed_seen = True
                    continue
                except AdmissionRefused:
                    shed_seen = True  # cap-full is shed-shaped backpressure
                    continue
                if name != primary:
                    self.spillovers += 1
                    plane.spill_in += 1
                if self.recorder is not None:
                    self.recorder.span(
                        "frontdoor_route", t0, trace_now(),
                        tid=SERVICE_TID, cat="federation",
                        args={"region": name, "origin": origin,
                              "attempts": attempts,
                              "spilled": name != primary},
                    )
                return "admitted", s, plane, attempts
            if attempts >= p.retry_budget:
                break
            delay_ms = self.backoff_ms(attempts)
            attempts += 1
            self.retries += 1
            await asyncio.sleep(delay_ms / 1000.0)
        outcome = "shed" if shed_seen else "failed"
        if outcome == "shed":
            self.sheds += 1
        else:
            self.failures += 1
        if self.recorder is not None:
            self.recorder.span(
                "frontdoor_route", t0, trace_now(),
                tid=SERVICE_TID, cat="federation",
                args={"region": "", "origin": origin,
                      "attempts": attempts, "outcome": outcome},
            )
        return outcome, None, None, attempts


class Federation:
    """The whole geo plane: per-region clusters, the front door, and the
    cross-region epoch path. Build it, `start()` it inside a running
    loop, `submit()` arrivals, `kill_region`/`recover_region` for chaos,
    `stop()` when drained."""

    def __init__(self, p, *, scheme=None, recorder=None,
                 logger: Logger = DEFAULT_LOGGER):
        regions, rtt = planet_preset(p.planet)
        self.geo = GeoConfig(
            regions=regions, rtt_ms=rtt, seed=p.geo_seed
        ).validate()
        self.p = p
        self.scheme = scheme or FakeScheme()
        self.recorder = recorder
        self.log = logger
        self.planes = [
            RegionPlane(name, i, p, scheme=self.scheme,
                        recorder=recorder, logger=logger)
            for i, name in enumerate(regions)
        ]
        self.by_name = {r.name: r for r in self.planes}
        self.front_door = FrontDoor(
            self.geo, self.planes, p, recorder=recorder, logger=logger
        )
        # federation-wide validator-set epoch (every healthy region's
        # cluster rotates together through quiesce_and)
        self.epoch = 0
        self.last_rotation_stall_s: dict[str, float] = {}

    def start(self) -> None:
        for r in self.planes:
            r.start()
        self.front_door.start()

    async def stop(self) -> None:
        await self.front_door.stop()
        for r in self.planes:
            if not r.killed:
                r.cluster.stop()

    def region_names(self) -> list[str]:
        return [r.name for r in self.planes]

    async def submit(self, origin: str, *, nodes: int, tier: str | None,
                     seed: int, on_done=None):
        return await self.front_door.submit(
            origin, nodes=nodes, tier=tier, seed=seed, on_done=on_done
        )

    # -- chaos: region kill + epoch-path recovery ---------------------------

    def kill_region(self, name: str) -> list[str]:
        """Stop `name`'s cluster mid-flight; returns the interrupted live
        sids for the caller to re-route. The front door learns of the
        death from its next probe or the first misrouted arrival."""
        return self.by_name[name].kill()

    async def recover_region(self, name: str) -> float:
        """Rebuild `name` and rejoin it via the epoch path: the fresh
        cluster plus every surviving region stage the next validator set
        and flip under quiesce_and (cross-region epoch rotation), so the
        rejoined region re-enters at the federation's new epoch rather
        than cold-starting at 0. Returns the worst per-region stall."""
        self.by_name[name].revive()
        return await self.rotate_epochs()

    async def rotate_epochs(self) -> float:
        """One federation-wide epoch rotation riding the existing
        stage -> quiesce -> flip choreography (lifecycle/epoch.py) on
        every healthy region; returns the worst gate-closed stall."""
        from handel_tpu.lifecycle.epoch import EpochManager

        pubkeys = [
            self.scheme.keygen(i)[1] for i in range(self.p.registry)
        ]
        worst = 0.0
        for plane in self.planes:
            if plane.killed:
                continue
            em = EpochManager(
                plane.cluster.service, plane.cluster.manager,
                logger=self.log,
            )
            await em.begin_rotation(pubkeys)
            stall = await em.commit_rotation()
            self.last_rotation_stall_s[plane.name] = stall
            worst = max(worst, stall)
        self.epoch += 1
        if self.recorder is not None:
            self.recorder.instant(
                "federation_epoch", tid=SERVICE_TID, cat="federation",
                args={"epoch": self.epoch},
            )
        return worst

    # -- reporters ----------------------------------------------------------

    def values(self) -> dict[str, float]:
        fd = self.front_door
        return {
            "regionsTotal": float(len(self.planes)),
            "regionsHealthy": float(
                sum(1 for r in self.planes if not r.killed)
            ),
            "frontDoorRetries": float(fd.retries),
            "spilloverCt": float(fd.spillovers),
            "frontDoorSheds": float(fd.sheds),
            "frontDoorFailures": float(fd.failures),
            # monotonic healthy->down mark-downs (passive + probe) so the
            # alert plane can difference mark-down bursts between scrapes
            "markdownCt": float(fd.markdowns),
            "probeRounds": float(fd.probe_rounds),
            "regionKills": float(sum(r.kills for r in self.planes)),
            "regionRecoveries": float(
                sum(r.recoveries for r in self.planes)
            ),
            "epoch": float(self.epoch),
        }

    def gauge_keys(self) -> set[str]:
        return {"regionsTotal", "regionsHealthy", "epoch"}

    def labeled_values(self) -> dict[str, dict[str, float]]:
        """{region name: per-region stats} for the `region`-labeled plane
        (handel_federation_*{region="..."}; `sim watch` federation rows)."""
        return {r.name: r.stats() for r in self.planes}

    def labeled_gauge_keys(self) -> set[str]:
        return {"regionHealthy", "sessionsLive", "shedRate", "epoch"}

"""Multi-session service drivers: one process or a small fleet of them.

`MultiSessionCluster` is the in-process form — K concurrent sessions
(service/session.py) sharing ONE `BatchVerifierService` on one event loop,
with an optional /metrics endpoint carrying the session-labeled plane.
`run_service` is the `sim serve` entry: it reads the `[service]` TOML
section (sim/config.py ServiceParams) and runs the session load either
in-process (processes = 1) or sharded over M worker node-processes
(service/worker.py), each worker multiplexing its share of sessions onto
its own shared verifier — "K sessions over M node-processes".

`HostDevice` adapts host schemes (fake, bn254 reference math) to the
service's device contract so the WHOLE launch path — tenant queue, DRR
fairness, cross-session coalescing, fill accounting, breaker — is
exercised without a chip: one `dispatch_multi` call is one "launch" whose
lanes may span sessions, messages and registries. Device schemes plug in
their real `BN254Device` instead (its `dispatch_multi` takes per-lane
messages, models/bn254_jax.py).
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

from handel_tpu.core.test_harness import FakeScheme
from handel_tpu.models import rlc
from handel_tpu.parallel.batch_verifier import BatchVerifierService
from handel_tpu.service.session import SessionManager


class HostDevice:
    """Device-shaped host verifier behind the shared service.

    `dispatch_multi(items)` — items are (msg, pubkeys, bitset, sig) — runs
    the scheme constructor's own batch_verify per (message, registry)
    group, synchronously (the service calls it in an executor thread), and
    returns the verdicts handle `fetch` hands back. `launch_ms` simulates
    a fixed device wall per launch (latency-shape experiments); 0 = as
    fast as the host math goes.

    `batch_check="rlc"` switches the launch to the random-linear-
    combination combined check (models/rlc.py): one M+1-pairing equation
    over the whole launch, bisection with fresh scalars down to the
    per-candidate oracle when it fails. Schemes without an RLC ops table
    (FakeScheme) silently stay per-candidate.
    """

    def __init__(self, constructor, batch_size: int = 64,
                 launch_ms: float = 0.0,
                 batch_check: str = "per_candidate", rlc_rng=None):
        self.constructor = constructor
        self.batch_size = batch_size
        self.launch_ms = launch_ms
        self.batch_check = rlc.validate_batch_check(batch_check)
        self._rlc_rng = rlc_rng
        self._rlc_ops = (
            rlc.host_ops_for(constructor) if batch_check == "rlc" else None
        )
        self.rlc_stats = rlc.RlcStats()
        self.dispatched = 0
        # epoch-rotation protocol parity with BN254Device (lifecycle/
        # epoch.py): host verification reads per-request pubkeys so there
        # is no resident bank to flip, but the soak/CI path must exercise
        # the same stage -> quiesce -> activate choreography end to end
        self.epoch = 0
        self._staged = None
        self.registry_stagings = 0
        self.registry_staged_ms = 0.0

    def stage_registry(self, registry_pubkeys, build_prefix: bool = True) -> int:
        self._staged = registry_pubkeys
        self.registry_stagings += 1
        return len(registry_pubkeys)

    def activate_staged(self) -> int:
        if self._staged is None:
            raise RuntimeError("no staged registry: call stage_registry first")
        self._staged = None
        self.epoch += 1
        return self.epoch

    def dispatch_multi(self, items):
        if self._rlc_ops is not None:
            verdicts = self._rlc_dispatch_multi(items)
        else:
            verdicts = [False] * len(items)
            groups: dict[tuple, list[int]] = {}
            for i, (msg, pubkeys, _, _) in enumerate(items):
                groups.setdefault((msg, id(pubkeys)), []).append(i)
            for (msg, _), idxs in groups.items():
                pubkeys = items[idxs[0]][1]
                reqs = [(items[i][2], items[i][3]) for i in idxs]
                for i, ok in zip(
                    idxs, self.constructor.batch_verify(msg, pubkeys, reqs)
                ):
                    verdicts[i] = bool(ok)
            # per-candidate pairing cost, for the rlc_smoke M+1 assertion:
            # each non-empty candidate is 2 Miller loops + 1 final exp
            live = sum(1 for it in items if it[2].cardinality() > 0)
            self.rlc_stats.miller_lanes += 2 * live
            self.rlc_stats.final_exp_lanes += live
        if self.launch_ms > 0:
            time.sleep(self.launch_ms / 1000.0)
        self.dispatched += 1
        return verdicts

    def _rlc_dispatch_multi(self, items):
        """RLC combined launch: aggregate each candidate's apk on the host,
        run one M+1-pairing check over every valid candidate (across
        message groups — that is the point), bisect on failure."""
        verdicts: list[bool] = [False] * len(items)
        cands: dict[int, tuple] = {}
        for i, (msg, pubkeys, bs, sig) in enumerate(items):
            if bs.cardinality() == 0 or getattr(sig, "point", None) is None:
                continue
            apk = self.constructor.aggregate_public_keys(pubkeys, bs)
            if getattr(apk, "point", None) is None:
                continue
            cands[i] = (msg, apk.point, sig.point)

        def combined(sub: list[int]) -> bool:
            return rlc.host_rlc_check(
                self._rlc_ops, [cands[i] for i in sub],
                rng=self._rlc_rng, stats=self.rlc_stats,
            )

        def oracle(i: int) -> bool:
            msg, pubkeys, bs, sig = items[i]
            self.rlc_stats.miller_lanes += 2
            self.rlc_stats.final_exp_lanes += 1
            return bool(
                self.constructor.batch_verify(msg, pubkeys, [(bs, sig)])[0]
            )

        for i, ok in rlc.bisect_verify(
            list(cands), combined, oracle, self.rlc_stats
        ).items():
            verdicts[i] = ok
        return verdicts

    def fetch(self, handle):
        return handle


class MultiSessionCluster:
    """K concurrent sessions sharing one BatchVerifierService in-process."""

    def __init__(
        self,
        sessions: int,
        nodes: int,
        *,
        threshold: int | None = None,
        scheme=None,
        device=None,
        batch_size: int = 64,
        max_sessions: int | None = None,
        session_ttl_s: float = 60.0,
        quantum: int = 8,
        max_pending_per_session: int = 4096,
        queue_capacity: int = 0,
        tier_cycle: tuple | list = (),
        max_delay_ms: float = 2.0,
        spawn_stagger_s: float = 0.0,
        metrics_port: int | None = None,
        seed_base: int = 0,
        config_tweak=None,
        devices: int = 1,
        mesh_devices: int = 0,
        mesh_batch_size: int = 8,
        batch_check: str = "per_candidate",
        recorder=None,
        alert_p=None,
    ):
        self.k = sessions
        self.nodes = nodes
        self.threshold = threshold
        self.spawn_stagger_s = spawn_stagger_s
        self.seed_base = seed_base
        self.config_tweak = config_tweak
        # SLO tiers (service/fairness.py TIERS) dealt round-robin across
        # the spawned sessions; empty = every tenant on the flat default
        self.tier_cycle = tuple(tier_cycle)
        scheme = scheme or FakeScheme()
        if device is None:
            if devices > 1:
                # fleet-of-chips serve path ([service] devices = N): one
                # host engine per lane, scheduled least-loaded-first
                # (parallel/plane.py) so the tenant queue fills K chips
                from handel_tpu.parallel.plane import host_plane

                device = host_plane(
                    scheme.constructor, devices, batch_size=batch_size,
                    batch_check=batch_check,
                )
            else:
                device = HostDevice(
                    scheme.constructor, batch_size=batch_size,
                    batch_check=batch_check,
                )
        self.service = BatchVerifierService(
            device,
            max_delay_ms=max_delay_ms,
            quantum=quantum,
            max_pending_per_session=max_pending_per_session,
            queue_capacity=queue_capacity,
            recorder=recorder,
        )
        if mesh_devices > 0:
            # latency plane ([service] mesh_devices = K): one whole-mesh
            # lane beside the per-chip throughput lanes — small gold-tier
            # launch groups ride it (parallel/mesh_plane.py ModePolicy)
            from handel_tpu.parallel.mesh_plane import (
                enable_latency_plane,
                host_mesh_engine,
            )

            enable_latency_plane(
                self.service,
                host_mesh_engine(
                    scheme.constructor,
                    devices=mesh_devices,
                    batch_size=mesh_batch_size,
                ),
            )
        # one shared ring across every session's nodes AND the verify
        # plane: session-tagged spans end to end (core/handel.py _sargs,
        # batch_verifier.py lane lifecycle `sessions` arg)
        self.recorder = recorder
        self.manager = SessionManager(
            service=self.service,
            scheme=scheme,
            max_sessions=max_sessions or sessions,
            session_ttl_s=session_ttl_s,
            recorder=recorder,
        )

        # live telemetry (core/metrics.py): the shared verifier plane plus
        # the session-labeled service plane — `sim watch --attach` renders
        # the per-session rows from exactly these families
        self.metrics = None
        self.metrics_server = None
        if metrics_port is not None:
            from handel_tpu.core.metrics import (
                MetricsRegistry,
                MetricsServer,
            )

            reg = MetricsRegistry()
            reg.register_values("device_verifier", self.service)
            # per-device rows beside the session dimension: one sample per
            # plane lane, e.g. handel_device_verifier_launches{device="3"}
            reg.register_labeled_values(
                "device_verifier", self.service.plane, label="device",
                gauges={"mode", "checkMode", "bisectionDepthMax"},
            )
            reg.register_values("service", self.manager)
            reg.register_labeled_values(
                "service",
                self.manager,
                label="session",
                gauges=self.manager.labeled_gauge_keys(),
            )
            reg.register_labeled_values(
                "penalty", self.manager.scorers, label="session"
            )
            reg.add_readiness(
                "sessions_spawned", lambda: self.manager.spawned_ct > 0
            )
            if recorder is not None:
                # ring occupancy / drops / span rate beside the service rows
                reg.register_values("trace", recorder)
            self.metrics = reg
            self.metrics_server = MetricsServer(reg, port=metrics_port).start()

        # serve-mode alert plane ([alerts] TOML section): breaker-storm
        # detection over the shared verify plane, ticked by run()'s loop
        # (serve has no LifecycleController) — /alerts and the
        # handel_alerts_*/handel_incidents_* families ride the same
        # metrics server as the session rows
        self.alerts = None
        self._alert_p = alert_p
        if alert_p is not None and alert_p.enabled:
            from handel_tpu.obs import AlertPlane, EwmaDetector

            ap = AlertPlane.from_params(
                alert_p, recorder=recorder,
                trace_source=(
                    (lambda: recorder.export()["traceEvents"])
                    if recorder is not None else None
                ),
            )
            ap.detectors.attach(
                "breaker-storm",
                lambda: self.service.values()["breakerTransitionsCt"],
                EwmaDetector(alpha=alert_p.ewma_alpha,
                             z_threshold=alert_p.z_threshold),
                min_consecutive=alert_p.min_consecutive,
                opens_incident=True,
                direction="up",
                hold_while=lambda: any(
                    l.breaker.state == "open"
                    for l in self.service.plane.lanes
                ),
            )
            ap.detectors.attach(
                "queue-depth",
                lambda: float(self.service.queue_depth()),
                EwmaDetector(alpha=alert_p.ewma_alpha,
                             z_threshold=alert_p.z_threshold),
                min_consecutive=max(2, alert_p.min_consecutive),
                direction="up",
            )
            ap.add_context(
                "open_breaker_lanes",
                lambda: [
                    l.index for l in self.service.plane.lanes
                    if l.breaker.state == "open"
                ],
            )
            self.alerts = ap
            if self.metrics is not None:
                ap.register_metrics(self.metrics)

    async def _alert_loop(self) -> None:
        while True:
            await asyncio.sleep(self._alert_p.tick_interval_s)
            self.alerts.tick()

    async def run(self, timeout: float = 120.0) -> dict:
        """Spawn + start every session, await all terminal states, and
        return the run summary (the bench/capture record shape)."""
        t0 = time.perf_counter()
        alert_task = (
            asyncio.ensure_future(self._alert_loop())
            if self.alerts is not None
            else None
        )
        try:
            for i in range(self.k):
                s = self.manager.spawn(
                    self.nodes,
                    threshold=self.threshold,
                    seed=self.seed_base + i,
                    config_tweak=self.config_tweak,
                    tier=self.tier_cycle[i % len(self.tier_cycle)]
                    if self.tier_cycle
                    else None,
                )
                self.manager.start(s.sid)
                if self.spawn_stagger_s > 0:
                    await asyncio.sleep(self.spawn_stagger_s)
            await self.manager.wait_all(timeout)
        finally:
            if alert_task is not None:
                alert_task.cancel()
        wall = time.perf_counter() - t0
        return self.summary(wall)

    def summary(self, wall_s: float) -> dict:
        mv = self.manager.values()
        sv = self.service.values()
        return {
            "sessions": self.k,
            "nodes_per_session": self.nodes,
            "completed": int(mv["sessionsCompleted"]),
            "expired": int(mv["sessionsExpired"]),
            "wall_s": round(wall_s, 3),
            # sustained finality rate: completed aggregation instances
            # (full threshold aggregates produced) per wall second
            "aggregates_per_s": round(mv["sessionsCompleted"] / wall_s, 3)
            if wall_s > 0
            else 0.0,
            "session_p50_s": round(mv["sessionCompletionP50S"], 4),
            "session_p99_s": round(mv["sessionCompletionP99S"], 4),
            # coalescing evidence: per-launch lane fill + cross-message mix
            "launch_fill_ratio": round(sv["launchFillRatio"], 4),
            "verifier_launches": int(sv["verifierLaunches"]),
            "verifier_candidates": int(sv["verifierCandidates"]),
            "coalesced_launches": int(sv["coalescedLaunches"]),
            "dedup_hit_rate": round(sv["dedupHitRate"], 4),
            "admission_refused": int(sv["admissionRefused"]),
            # lifecycle plane: SLO shedding, epoch rotation, elasticity
            "admission_shed": int(sv["admissionShed"]),
            "shed_rate": round(sv["shedRate"], 4),
            "epoch": int(sv["epoch"]),
            "quiesce_ct": int(sv["quiesceCt"]),
            "last_quiesce_stall_ms": round(sv["lastQuiesceStallMs"], 3),
            "tier_quantiles": self.manager.tier_quantiles(),
            # fleet plane: per-device launch counts (multichip smoke
            # asserts every device dispatched) + the scheduler audit
            "devices": len(self.service.plane),
            "device_launches": [
                lane.launches for lane in self.service.plane.lanes
            ],
            "sched_idle_violations": int(
                self.service.plane.idle_violations
            ),
        }

    def stop(self) -> None:
        self.manager.stop()
        self.service.stop()
        if self.metrics_server is not None:
            self.metrics_server.stop()


def _split(total: int, parts: int) -> list[int]:
    """total sessions over parts workers, remainder on the first ones."""
    base, rem = divmod(total, max(1, parts))
    return [base + (1 if i < rem else 0) for i in range(parts)]


async def run_in_process(cfg, *, seed_base: int = 0,
                         metrics_port: int | None = None,
                         timeout: float | None = None) -> dict:
    """One worker's share: build a MultiSessionCluster from the TOML
    `[service]` section and run it to completion."""
    p = cfg.service
    scheme = None
    if cfg.scheme not in ("", "fake"):
        from handel_tpu.models.registry import is_device_scheme, new_scheme

        if is_device_scheme(cfg.scheme):
            raise ValueError(
                f"sim serve: device scheme {cfg.scheme!r} needs a shared "
                f"registry across sessions — run it with scheme = 'fake' "
                f"or a host scheme for now (ROADMAP item 3 follow-up)"
            )
        scheme = new_scheme(cfg.scheme)

    def tweak(node_cfg, i):
        node_cfg.update_period = p.period_ms / 1000.0

    cluster = MultiSessionCluster(
        p.sessions,
        p.nodes,
        threshold=p.threshold or None,
        scheme=scheme,
        devices=p.devices,
        mesh_devices=p.mesh_devices,
        mesh_batch_size=p.mesh_batch_size,
        batch_check=p.batch_check,
        batch_size=p.batch_size or cfg.batch_size,
        max_sessions=p.max_sessions or None,
        session_ttl_s=p.session_ttl_s,
        quantum=p.quantum,
        max_pending_per_session=p.max_pending_per_session,
        queue_capacity=p.queue_capacity,
        tier_cycle=[t.strip() for t in p.tiers.split(",") if t.strip()],
        spawn_stagger_s=p.spawn_stagger_ms / 1000.0,
        metrics_port=metrics_port,
        seed_base=seed_base,
        config_tweak=tweak,
        alert_p=getattr(cfg, "alerts", None),
    )
    try:
        return await cluster.run(timeout or cfg.max_timeout_s)
    finally:
        cluster.stop()


def merge_summaries(parts: list[dict]) -> dict:
    """Fleet summary from per-worker summaries: counts sum, rates sum
    (workers run concurrently), latency percentiles take the worst-case
    worker (conservative — exact merge would need the raw samples),
    fill/dedup weight by launches."""
    out = {
        "sessions": sum(p["sessions"] for p in parts),
        "nodes_per_session": parts[0]["nodes_per_session"] if parts else 0,
        "completed": sum(p["completed"] for p in parts),
        "expired": sum(p["expired"] for p in parts),
        "wall_s": max((p["wall_s"] for p in parts), default=0.0),
        "aggregates_per_s": round(
            sum(p["aggregates_per_s"] for p in parts), 3
        ),
        "session_p50_s": max((p["session_p50_s"] for p in parts), default=0.0),
        "session_p99_s": max((p["session_p99_s"] for p in parts), default=0.0),
        "verifier_launches": sum(p["verifier_launches"] for p in parts),
        "verifier_candidates": sum(p["verifier_candidates"] for p in parts),
        "coalesced_launches": sum(p["coalesced_launches"] for p in parts),
        "admission_refused": sum(p["admission_refused"] for p in parts),
        "admission_shed": sum(p.get("admission_shed", 0) for p in parts),
        # conservative: the worst worker's shed rate (exact needs raws)
        "shed_rate": max((p.get("shed_rate", 0.0) for p in parts), default=0.0),
        # fleet plane: each worker owns its own device plane, so the rows
        # concatenate (older workers without the keys contribute nothing)
        "devices": sum(p.get("devices", 1) for p in parts),
        "device_launches": [
            n for p in parts for n in p.get("device_launches", [])
        ],
        "sched_idle_violations": sum(
            p.get("sched_idle_violations", 0) for p in parts
        ),
        "workers": len(parts),
    }
    launches = out["verifier_launches"]
    out["launch_fill_ratio"] = (
        round(
            sum(p["launch_fill_ratio"] * p["verifier_launches"]
                for p in parts) / launches,
            4,
        )
        if launches
        else 0.0
    )
    hits = sum(
        p["dedup_hit_rate"] * p["verifier_candidates"] for p in parts
    )
    out["dedup_hit_rate"] = (
        round(hits / out["verifier_candidates"], 4)
        if out["verifier_candidates"]
        else 0.0
    )
    return out


async def run_service(cfg, workdir: str, config_path: str = "") -> dict:
    """The `sim serve` orchestrator: K sessions over M node-processes.

    processes = 1 runs in this process. Otherwise M workers
    (service/worker.py) each run their share of sessions against their own
    shared verifier; per-worker summaries merge into one record, written to
    `<workdir>/service_summary.json` either way.
    """
    from handel_tpu.sim.config import dump_config

    p = cfg.service
    if p.sessions <= 0:
        raise ValueError("no [service] section (service.sessions must be > 0)")
    os.makedirs(workdir, exist_ok=True)
    if not config_path:
        config_path = os.path.join(workdir, "serve.toml")
        with open(config_path, "w") as f:
            f.write(dump_config(cfg))

    metrics_ports: list[int] = []
    if cfg.metrics:
        from handel_tpu.sim.platform import free_ports, write_metrics_ports

        metrics_ports = free_ports(max(1, p.processes))
        write_metrics_ports(
            workdir, 0, dict(enumerate(metrics_ports))
        )

    if p.processes <= 1:
        summary = await run_in_process(
            cfg,
            metrics_port=metrics_ports[0] if metrics_ports else None,
        )
        summary["workers"] = 1
    else:
        shares = _split(p.sessions, p.processes)
        procs = []
        for i, share in enumerate(shares):
            if share <= 0:
                continue
            cmd = [
                sys.executable,
                "-m",
                "handel_tpu.service.worker",
                "--config",
                config_path,
                "--index",
                str(i),
                "--sessions",
                str(share),
            ]
            if metrics_ports:
                cmd += ["--metrics-port", str(metrics_ports[i])]
            procs.append(
                await asyncio.create_subprocess_exec(
                    *cmd,
                    stdout=asyncio.subprocess.PIPE,
                    stderr=asyncio.subprocess.PIPE,
                )
            )
        outs = await asyncio.gather(*(pr.communicate() for pr in procs))
        parts: list[dict] = []
        for pr, (out, err) in zip(procs, outs):
            if pr.returncode != 0:
                sys.stderr.write(err.decode(errors="replace"))
                raise RuntimeError(
                    f"service worker failed (rc={pr.returncode})"
                )
            for line in out.decode().splitlines():
                if line.startswith("SERVICE_RESULT "):
                    parts.append(json.loads(line[len("SERVICE_RESULT "):]))
        if len(parts) != len(procs):
            raise RuntimeError(
                f"{len(parts)}/{len(procs)} workers reported a summary"
            )
        summary = merge_summaries(parts)

    summary["scheme"] = cfg.scheme
    summary["ok"] = (
        summary["expired"] == 0
        and summary["completed"] == summary["sessions"]
    )
    with open(os.path.join(workdir, "service_summary.json"), "w") as f:
        json.dump(summary, f, indent=1)
        f.write("\n")
    return summary

# Test tiers (CI mirror; reference CI = `go test -v ./...`,
# .circleci/config.yml:26-28 — here split so the fast tier stays minutes-fast
# on one core even with a cold XLA compile cache).

PY ?= python

.PHONY: test test-fast test-slow test-all bench dryrun

# fast tier: protocol + transports + sim harness + cached JAX kernel tests
test-fast:
	$(PY) -m pytest tests/ -x -q

# reference-scale tier: 333-node failures, 37-node real crypto, BLS12-381 e2e
test-slow:
	$(PY) -m pytest tests/ -x -q -m slow

test-all:
	$(PY) -m pytest tests/ -x -q -m ""

test: test-fast

bench:
	$(PY) bench.py

dryrun:
	GRAFT_DRYRUN_DEVICES=8 $(PY) __graft_entry__.py

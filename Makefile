# Test tiers (CI mirror; reference CI = `go test -v ./...`,
# .circleci/config.yml:26-28 — here split so the fast tier stays minutes-fast
# on one core even with a cold XLA compile cache).
#
# Measured on this image's single core: the pre-split full tier (fast +
# kernel modules) ran 181 tests in 54:21 with a warm compile cache —
# XLA-compile-bound, not runtime-bound — so the JAX kernel modules
# (test_{fp,tower,curve,pairing,bls12_381}_jax, test_bn254_device,
# test_bench) are slow-tier: nightly/CI coverage via test-slow/test-all.
# The fast tier keeps the pure-Python curve oracles, the full protocol/
# sim/transport planes, and the 8-device sharding guards — measured
# post-split: 135 tests in 2:00 on the same core (warm cache), restoring
# the minutes-fast contract.

PY ?= python

.PHONY: test test-fast test-slow test-all bench dryrun

# fast tier: protocol + transports + sim harness + oracle + sharding guards
test-fast:
	$(PY) -m pytest tests/ -x -q

# compile-heavy + reference-scale tier: JAX kernel modules, 333-node
# failures, 37-node real crypto, BLS12-381 e2e
test-slow:
	$(PY) -m pytest tests/ -x -q -m slow

test-all:
	$(PY) -m pytest tests/ -x -q -m ""

test: test-fast

bench:
	$(PY) bench.py

dryrun:
	GRAFT_DRYRUN_DEVICES=8 $(PY) __graft_entry__.py
